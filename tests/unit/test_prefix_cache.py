"""Prefix cache (ISSUE 5): the content-addressed refcounted block index
(`inference/v2/prefix_cache.py`) and its allocator/state-manager seams.

The centerpiece is the randomized stress test: interleaved
alloc/match/share/decref/evict/trim against a reference-counting model
checker — no double free (the allocator now detects it exactly), no freed
block aliasing into a live block table, and full capacity recovery at
drain. This covers the PR 3 interplay where the pipelined EOS rollback's
deferred ``trim_blocks`` must decref shared blocks instead of freeing
them."""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (
    BlockedAllocator,
    BlockedKVCache,
    PrefixCache,
    RaggedInferenceConfig,
    StateManager,
)
from deepspeed_tpu.inference.v2.blocked_allocator import OutOfBlocksError


class TestAllocatorGuards:
    def test_double_free_detected_exactly(self):
        a = BlockedAllocator(8)
        blocks = a.allocate(3)
        a.free(blocks[:1])
        with pytest.raises(RuntimeError, match="double free of block"):
            a.free(blocks[:1])
        # the failed free must not have corrupted the free list
        assert a.free_blocks == 6

    def test_partial_double_free_rolls_nothing_in(self):
        a = BlockedAllocator(4)
        b = a.allocate(2)
        a.free([b[0]])
        with pytest.raises(RuntimeError):
            a.free([b[0], b[1]])       # first id already free
        assert a.free_blocks == 3      # b[1] NOT silently freed

    def test_same_call_duplicate_detected(self):
        a = BlockedAllocator(8)
        b = a.allocate(1)[0]
        # the duplicate is WITHIN one call: neither copy is in the free
        # set when checked, so only a same-call guard catches it (a miss
        # would hand block b to two later allocate() calls)
        with pytest.raises(RuntimeError, match="double free"):
            a.free([b, b])
        assert a.free_blocks == 7      # nothing rolled in


class TestPrefixCacheIndex:
    def _pc(self, bs=4, **kw):
        return PrefixCache(bs, **kw)

    def test_identity_includes_parent_chain(self):
        pc = self._pc()
        a = pc.insert(None, (1, 2, 3, 4), 0)
        b = pc.insert(a, (9, 9, 9, 9), 1)
        # the SAME tokens under a different prefix are a different block
        c = pc.insert(None, (9, 9, 9, 9), 2)
        assert b is not None and c is not None and b is not c
        ents, cow, n = pc.match([1, 2, 3, 4, 9, 9, 9, 9, 5])
        assert [e.block for e in ents] == [0, 1]
        ents2, _, _ = pc.match([9, 9, 9, 9, 5])
        assert [e.block for e in ents2] == [2]

    def test_match_leaves_last_token(self):
        pc = self._pc()
        a = pc.insert(None, (1, 2, 3, 4), 0)
        pc.insert(a, (5, 6, 7, 8), 1)
        # the whole query is cached — the match must still leave >= 1
        # token for the engine's final chunk (last-token logits)
        ents, cow, n = pc.match([1, 2, 3, 4, 5, 6, 7, 8])
        assert [e.block for e in ents] == [0]
        assert cow is not None and cow.block == 1 and n == 3

    def test_cow_longest_agreeing_child(self):
        pc = self._pc()
        root = pc.insert(None, (1, 2, 3, 4), 0)
        pc.insert(root, (5, 6, 0, 0), 1)
        pc.insert(root, (5, 6, 7, 0), 2)
        ents, cow, n = pc.match([1, 2, 3, 4, 5, 6, 7, 9, 9])
        assert [e.block for e in ents] == [0]
        assert cow.block == 2 and n == 3

    def test_eviction_leaf_first_lru(self):
        pc = self._pc()
        a = pc.insert(None, (1,) * 4, 0)
        b = pc.insert(a, (2,) * 4, 1)
        c = pc.insert(None, (3,) * 4, 2)
        for e in (a, b, c):
            pc.release_block(e.block)      # refs 1 -> 0, in insert order
        # a has a cached child: only b and c are leaf-evictable; b was
        # released before c -> LRU takes b; that makes a a leaf, and a
        # (released before c) goes next, then c
        assert pc.evict(1) == [1]
        assert pc.evict(2) == [0, 2]
        assert pc.cached_blocks == 0

    def test_refcounted_blocks_not_evictable(self):
        pc = self._pc()
        a = pc.insert(None, (1,) * 4, 0)
        pc.acquire(a)                      # a matcher holds it
        pc.release_block(0)                # registering seq lets go
        assert pc.evictable_blocks == 0 and pc.evict(4) == []
        pc.release_block(0)
        assert pc.evictable_blocks == 1

    def test_refcount_underflow_raises(self):
        pc = self._pc()
        pc.insert(None, (1,) * 4, 0)
        pc.release_block(0)
        with pytest.raises(RuntimeError, match="underflow"):
            pc.release_block(0)

    def test_insert_duplicate_not_adopted(self):
        pc = self._pc()
        assert pc.insert(None, (1,) * 4, 0) is not None
        assert pc.insert(None, (1,) * 4, 5) is None
        assert pc.cached_blocks == 1

    def test_max_blocks_cap_evicts_or_skips(self):
        pc = self._pc(max_blocks=2)
        a = pc.insert(None, (1,) * 4, 0)
        b = pc.insert(None, (2,) * 4, 1)
        # everything referenced: cap reached, insert skipped
        assert pc.insert(None, (3,) * 4, 2) is None
        pc.release_block(0)
        # a is cold now: the capped insert evicts it and adopts
        e = pc.insert(None, (4,) * 4, 3)
        assert e is not None
        assert pc.collect_pending_free() == [0]
        assert pc.cached_blocks == 2

    def test_fifo_policy_orders_by_insertion(self):
        pc = self._pc(policy="fifo")
        pc.insert(None, (1,) * 4, 0)
        pc.insert(None, (2,) * 4, 1)
        pc.release_block(1)                # released FIRST
        pc.release_block(0)
        assert pc.evict(1) == [0]          # but 0 was inserted first


class TestBatchedPutRegistration:
    def test_no_graft_under_foreign_chain(self):
        """Batched put() race: two fresh prompts sharing a prefix both
        match (empty cache) BEFORE either registers. The first writer
        owns the chain; the second's copies stay private — it must NOT
        graft its extra full block under the foreign chain, which would
        let the chain's ancestors hit refcount 0 while a referenced
        child stays cached (breaking refs(parent) >= refs(child) and
        overcounting evictable capacity)."""
        import jax.numpy as jnp
        bs = 4
        cfg = RaggedInferenceConfig(
            max_seqs=4, chunk_size=8, block_size=bs, num_blocks=16,
            max_blocks_per_seq=8, dtype="float32", prefix_cache=True)
        kv = BlockedKVCache(cfg, 1, 1, 4, jnp.float32)
        pc = PrefixCache(bs)
        kv.attach_prefix_cache(pc)
        sm = StateManager(cfg, kv)
        sm.prefix = pc
        shared = [1, 2, 3, 4, 5, 6, 7, 8]
        s0 = sm.put_tokens(0, shared + [9])                    # 2 full blocks
        s1 = sm.put_tokens(1, shared + [10, 11, 12, 13, 14])   # 3 full blocks
        sm.match_prefix(s0)
        sm.match_prefix(s1)            # nothing cached yet: both miss
        for s in (s0, s1):
            n = s.in_flight
            sm.ensure_blocks(s, n)
            del s.pending_tokens[:n]
            s.seen_tokens += n
        sm.register_prefix(s0)         # first writer wins the shared chain
        sm.register_prefix(s1)
        pc.check_invariants()
        sm.flush(0)                    # chain goes cold; must ALL be
        pc.check_invariants()          # evictable — no stranded child
        assert pc.evictable_blocks == pc.cached_blocks == 2
        sm.flush(1)
        kv.allocator.free(pc.evict(16))
        assert pc.cached_blocks == 0
        assert kv.allocator.free_blocks == 16

    def test_rejected_spec_run_on_shared_chain_decrefs_once(self):
        """The ISSUE-12 rollback exactness case: two sequences share a
        cached prefix chain; one runs a speculative verify window that
        is mostly REJECTED. The multi-token trim must release only the
        over-allocated private blocks and decref nothing it does not
        own — the shared chain's refcounts stay exact (one per
        referencing sequence) and no double free is possible."""
        import jax.numpy as jnp
        bs = 4
        cfg = RaggedInferenceConfig(
            max_seqs=4, chunk_size=8, block_size=bs, num_blocks=16,
            max_blocks_per_seq=8, dtype="float32", prefix_cache=True)
        kv = BlockedKVCache(cfg, 1, 1, 4, jnp.float32)
        pc = PrefixCache(bs)
        kv.attach_prefix_cache(pc)
        sm = StateManager(cfg, kv)
        sm.prefix = pc
        shared = [1, 2, 3, 4, 5, 6, 7, 8]
        s0 = sm.put_tokens(0, shared + [9])
        sm.match_prefix(s0)
        n = s0.in_flight
        sm.ensure_blocks(s0, n)
        del s0.pending_tokens[:n]
        s0.seen_tokens += n
        sm.register_prefix(s0)
        s1 = sm.put_tokens(1, shared + [10])
        sm.match_prefix(s1)               # hits the registered chain
        assert len(s1.shared) == 2
        for e in pc._by_block.values():
            assert e.refs == 2            # both sequences on the chain
        n = s1.in_flight
        sm.ensure_blocks(s1, n)
        del s1.pending_tokens[:n]
        s1.seen_tokens += n
        # speculative verify window: K+1 = 6 positions appended, only 1
        # accepted -> trim retracts 5, freeing the over-allocation
        free0 = kv.allocator.free_blocks
        sm.ensure_blocks(s1, 6)
        seen0 = s1.seen_tokens
        s1.seen_tokens = seen0 + 6
        s1.seen_tokens = seen0 + 1        # host accepted 1 token
        freed = sm.trim_blocks(s1)
        assert freed >= 1
        assert kv.allocator.free_blocks == free0
        pc.check_invariants()
        pc.assert_exact_refs([s0, s1])    # chain refs STILL exactly 2
        for e in pc._by_block.values():
            assert e.refs == 2
        # a second trim at the same seen is a no-op (nothing left over)
        assert sm.trim_blocks(s1) == 0
        sm.flush(0)
        sm.flush(1)
        pc.assert_exact_refs([])
        kv.allocator.free(pc.evict(16))
        assert kv.allocator.free_blocks == 16


class TestRandomizedRefcountModel:
    """The satellite model checker: random interleavings of the full
    block lifecycle against a shadow ownership model."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_stress_no_double_free_no_aliasing_full_drain(self, seed):
        import jax.numpy as jnp
        rng = np.random.default_rng(seed)
        bs, num_blocks = 4, 48
        cfg = RaggedInferenceConfig(
            max_seqs=4, chunk_size=8, block_size=bs, num_blocks=num_blocks,
            max_blocks_per_seq=8, dtype="float32", prefix_cache=True)
        kv = BlockedKVCache(cfg, 1, 1, 4, jnp.float32)
        pc = PrefixCache(bs, policy=rng.choice(["lru", "fifo"]))
        kv.attach_prefix_cache(pc)
        sm = StateManager(cfg, kv)
        sm.prefix = pc

        # a small prompt alphabet so random prompts actually collide
        vocab, next_uid = 3, [0]
        live = {}

        def new_seq():
            uid = next_uid[0]
            next_uid[0] += 1
            n = int(rng.integers(2, 29))
            toks = rng.integers(0, vocab, n).tolist()
            try:
                seq = sm.put_tokens(uid, toks)
            except ValueError:
                return
            sm.match_prefix(seq)       # copies would be device work: the
            #                            stress checks bookkeeping only
            # prefill the rest in random chunk sizes
            while seq.in_flight:
                c = int(rng.integers(1, 9))
                c = min(c, seq.in_flight)
                try:
                    sm.ensure_blocks(seq, c)
                except OutOfBlocksError:
                    if not live:        # nothing to victimize: drop it
                        sm.flush(uid)
                        return
                    # evict pressure path exercised; give up on this seq
                    sm.flush(uid)
                    return
                del seq.pending_tokens[:c]
                seq.seen_tokens += c
            sm.register_prefix(seq)
            live[uid] = seq

        def decode_some(uid):
            seq = live[uid]
            n = int(rng.integers(1, 9))
            try:
                sm.ensure_blocks(seq, n)
            except OutOfBlocksError:
                return
            seq.seen_tokens += n

        def trim(uid):
            seq = live[uid]
            # retract a random speculative overrun (never into the prompt)
            prompt = seq.prompt_len
            if seq.seen_tokens > prompt:
                seq.seen_tokens -= int(
                    rng.integers(0, seq.seen_tokens - prompt + 1))
            sm.trim_blocks(seq)

        def spec_round(uid):
            # the decode_spec lifecycle as one op: allocate KV for a
            # pinned K+1-token verify window, then commit only the
            # accepted prefix and trim the rest — a rejected run on a
            # shared-prefix chain must decref each released shared
            # block exactly once (the conservation + refcount-drift
            # asserts in check() are the oracle)
            seq = live[uid]
            L = int(rng.integers(2, 8))
            try:
                sm.ensure_blocks(seq, L)
            except OutOfBlocksError:
                return
            seen0 = seq.seen_tokens
            seq.seen_tokens = seen0 + L          # verify wrote L slots
            accepted = int(rng.integers(1, L + 1))
            seq.seen_tokens = seen0 + accepted   # host accepts a prefix
            sm.trim_blocks(seq)

        def check():
            alloc = kv.allocator
            free = set(alloc._free)
            assert len(free) == alloc.free_blocks          # list == set
            pc.check_invariants()
            pc.assert_exact_refs(live.values())
            cached = set(pc._by_block)
            assert not free & cached, "freed block still cached"
            refs = {}
            for seq in live.values():
                tabs = set(seq.kv_blocks)
                assert len(tabs) == len(seq.kv_blocks), \
                    "block repeated in one table"
                assert not any(alloc.is_free(b) for b in tabs), \
                    "freed block aliased into a live block table"
                for b in seq.kv_blocks:
                    if b in seq.shared:
                        assert b in cached, "shared block not cached"
                        refs[b] = refs.get(b, 0) + 1
                    else:
                        # a private block is owned by exactly one table
                        assert refs.setdefault(b, "private") == "private"
            for b, n in refs.items():
                if n != "private":
                    assert pc.entry_of(b).refs == n, \
                        f"refcount drift on block {b}"
            # conservation: every block is free, cached, or exactly one
            # sequence's private block
            private = {b for s in live.values() for b in s.kv_blocks
                       if b not in s.shared}
            assert len(free) + len(cached) + len(private) == num_blocks

        for _ in range(300):
            op = rng.integers(0, 5)
            if op == 0 or not live:
                new_seq()
            elif op == 1:
                decode_some(int(rng.choice(list(live))))
            elif op == 2:
                trim(int(rng.choice(list(live))))
            elif op == 3:
                spec_round(int(rng.choice(list(live))))
            else:
                uid = int(rng.choice(list(live)))
                sm.flush(uid)
                del live[uid]
            check()

        # drain: flush everything, then evict the whole cache — the
        # allocator must recover FULL capacity
        for uid in list(live):
            sm.flush(uid)
        live.clear()
        check()
        kv.allocator.free(pc.evict(num_blocks))
        assert pc.cached_blocks == 0
        assert kv.allocator.free_blocks == num_blocks
