"""Launcher tests — hostfile parsing, filters, runner command construction,
local spawn env; mirrors the reference's ``tests/unit/launcher/``."""

import subprocess
import sys

import pytest

from deepspeed_tpu.env_report import collect_report
from deepspeed_tpu.launcher.hostfile import (
    HostfileError,
    filter_hosts,
    parse_hostfile,
)
from deepspeed_tpu.launcher.multinode_runner import (
    OpenMPIRunner,
    PDSHRunner,
    SlurmRunner,
    SSHRunner,
)
from deepspeed_tpu.launcher.runner import build_parser, resolve_hosts


HOSTFILE = """
# cluster
worker-0 slots=4
worker-1 slots=4
worker-2
"""


class TestHostfile:
    def test_parse(self):
        hosts = parse_hostfile(HOSTFILE)
        assert hosts == {"worker-0": 4, "worker-1": 4, "worker-2": 1}

    def test_duplicate_rejected(self):
        with pytest.raises(HostfileError, match="duplicate"):
            parse_hostfile("a slots=1\na slots=2")

    def test_bad_line_rejected(self):
        with pytest.raises(HostfileError):
            parse_hostfile("host slots=banana")

    def test_empty_rejected(self):
        with pytest.raises(HostfileError, match="empty"):
            parse_hostfile("# nothing\n")

    def test_include_filter(self):
        hosts = parse_hostfile(HOSTFILE)
        out = filter_hosts(hosts, include="worker-0@worker-2")
        assert list(out) == ["worker-0", "worker-2"]

    def test_include_slots(self):
        hosts = parse_hostfile(HOSTFILE)
        out = filter_hosts(hosts, include="worker-0:0,2")
        assert out == {"worker-0": 2}

    def test_exclude_filter(self):
        hosts = parse_hostfile(HOSTFILE)
        out = filter_hosts(hosts, exclude="worker-1")
        assert list(out) == ["worker-0", "worker-2"]

    def test_include_exclude_conflict(self):
        with pytest.raises(HostfileError, match="mutually exclusive"):
            filter_hosts(parse_hostfile(HOSTFILE), include="a", exclude="b")

    def test_unknown_host_rejected(self):
        with pytest.raises(HostfileError, match="unknown"):
            filter_hosts(parse_hostfile(HOSTFILE), include="nope")


class TestRunners:
    def _mk(self, cls):
        return cls(["h0", "h1"], "h0:7777", "train.py", ["--lr", "0.1"],
                   {"FOO": "bar"})

    def test_ssh_one_cmd_per_host_with_rank(self):
        cmds = self._mk(SSHRunner).commands()
        assert len(cmds) == 2
        assert cmds[0][0] == "ssh" and cmds[0][-2] == "h0"
        assert "DSTPU_PROCESS_ID=0" in cmds[0][-1]
        assert "DSTPU_PROCESS_ID=1" in cmds[1][-1]
        assert "DSTPU_NUM_PROCESSES=2" in cmds[0][-1]
        assert "DSTPU_COORDINATOR=h0:7777" in cmds[0][-1]
        assert "FOO=bar" in cmds[0][-1]

    def test_pdsh(self):
        cmds = self._mk(PDSHRunner).commands()
        assert cmds[0][0] == "pdsh" and "-w" in cmds[0]

    def test_openmpi_single_cmd(self):
        cmds = self._mk(OpenMPIRunner).commands()
        assert len(cmds) == 1
        assert cmds[0][0] == "mpirun"
        assert "-np" in cmds[0] and "2" in cmds[0]

    def test_slurm_single_cmd(self):
        cmds = self._mk(SlurmRunner).commands()
        assert len(cmds) == 1 and cmds[0][0] == "srun"
        assert "--nodelist=h0,h1" in cmds[0]


class TestRunnerCLI:
    def test_parser_defaults(self):
        args = build_parser().parse_args(["train.py", "--x", "1"])
        assert args.user_script == "train.py"
        assert args.user_args == ["--x", "1"]
        assert args.launcher == "ssh"

    def test_resolve_hosts_num_nodes(self, tmp_path):
        hf = tmp_path / "hosts"
        hf.write_text(HOSTFILE)
        args = build_parser().parse_args(
            ["--hostfile", str(hf), "--num_nodes", "2", "t.py"])
        assert resolve_hosts(args) == ["worker-0", "worker-1"]

    def test_local_exec_roundtrip(self, tmp_path):
        """`dstpu script.py` single-host path actually runs the script."""
        script = tmp_path / "probe.py"
        out = tmp_path / "out.txt"
        script.write_text(f"open({str(out)!r}, 'w').write('ran')\n")
        rc = subprocess.call(
            [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
             str(script)])
        assert rc == 0
        assert out.read_text() == "ran"


class TestEnvReport:
    def test_report_collects(self):
        lines = collect_report()
        text = "\n".join(lines)
        assert "deepspeed_tpu" in text
        assert "flash_attention" in text
        assert "[FAIL]" not in text
