"""Mesh/topology tests — analogue of reference tests/unit/runtime/pipe topology tests."""

import pytest

from deepspeed_tpu.config import Config, ConfigError, MeshConfig
from deepspeed_tpu.parallel import build_mesh


def test_auto_data_axis(devices8):
    topo = build_mesh(MeshConfig())
    assert topo.dp_world_size == 8
    assert topo.world_size == 8


def test_mixed_axes(devices8):
    topo = build_mesh(MeshConfig(model=2, seq=2))
    assert topo.tp_world_size == 2
    assert topo.sp_world_size == 2
    assert topo.dp_world_size == 2
    assert topo.world_size == 8


def test_zero_axes_fuse_seq_and_data(devices8):
    topo = build_mesh(MeshConfig(seq=2))
    assert set(topo.zero_axes) == {"seq", "data"}
    assert topo.zero_world_size == 8


def test_indivisible_raises(devices8):
    with pytest.raises(ConfigError):
        build_mesh(MeshConfig(model=3))


def test_explicit_mismatch_raises(devices8):
    with pytest.raises(ConfigError):
        build_mesh(MeshConfig(data=3, model=2))


def test_batch_sharding_spec(devices8):
    topo = build_mesh(MeshConfig(model=2))
    s = topo.batch_sharding()
    assert s.spec == ("data",) or tuple(s.spec) == (("data",),)
