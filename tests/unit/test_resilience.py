"""Resilience layer — fault injection, self-healing checkpoints,
preemption-aware elasticity, step watchdog (docs/resilience.md).

The crash/resume acceptance bar: a mid-save injected crash (torn
``state.npz``) followed by restart resumes from the newest VALID tag with
identical ``global_steps`` and optimizer state, and ``latest`` is only
ever updated after a fully-validated tag exists on disk.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.checkpoint.engine_checkpoint import (
    LATEST_FILE,
    QUARANTINE_SUFFIX,
    STATE_FILE,
    find_valid_tag,
    publish_latest,
    validate_checkpoint_dir,
)
from deepspeed_tpu.models.gpt2 import GPT2Config, make_model
from deepspeed_tpu.resilience import (
    FAULT_SITES,
    FaultInjector,
    InjectedFault,
    RestartLedger,
    StepWatchdog,
    set_fault_injector,
)


@pytest.fixture(autouse=True)
def _clean_injector():
    yield
    set_fault_injector(None)


def _engine(lr=1e-2):
    cfg_model = GPT2Config.tiny(dtype=jnp.float32)
    model, init_fn, loss_fn = make_model(cfg_model)
    params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=17)
    engine, _, _, _ = dstpu.initialize(
        loss_fn=loss_fn, params=params, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": lr}},
            "steps_per_print": 1000,
            "checkpoint": {"retry_backoff_s": 0.01},
        })
    return engine


def _batch(engine, seed=0):
    rng = np.random.RandomState(seed)
    B = engine.config.train_batch_size
    return {"tokens": jnp.asarray(rng.randint(0, 512, size=(B, 18)),
                                  jnp.int32)}


def _params_snapshot(engine):
    return [np.array(jax.device_get(x))
            for x in jax.tree_util.tree_leaves(engine.state.params)]


def _opt_snapshot(engine):
    return [np.array(jax.device_get(x))
            for x in jax.tree_util.tree_leaves(engine.state.opt_state)]


# ------------------------------------------------------------------------- #
# fault injector mechanics
# ------------------------------------------------------------------------- #

class TestFaultInjector:
    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError):
            FaultInjector(site="nope")

    def test_raise_mode_and_times(self):
        inj = FaultInjector(site="pre_save", mode="raise", times=2)
        for _ in range(2):
            with pytest.raises(InjectedFault):
                inj.maybe_fire("pre_save")
        inj.maybe_fire("pre_save")            # exhausted: no-op
        inj.maybe_fire("mid_save")            # different site: no-op

    def test_skip_counts_arrivals(self):
        inj = FaultInjector(site="pre_save", mode="raise", skip=2)
        inj.maybe_fire("pre_save")
        inj.maybe_fire("pre_save")
        with pytest.raises(InjectedFault):
            inj.maybe_fire("pre_save")

    def test_step_gating(self):
        inj = FaultInjector(site="step", mode="raise", at_step=3)
        inj.maybe_fire("step", step=0)
        inj.maybe_fire("step", step=2)
        with pytest.raises(InjectedFault):
            inj.maybe_fire("step", step=3)

    def test_once_file_disarms(self, tmp_path):
        marker = str(tmp_path / "fired")
        inj = FaultInjector(site="pre_save", mode="raise", once_file=marker)
        with pytest.raises(InjectedFault):
            inj.maybe_fire("pre_save")
        assert os.path.exists(marker)
        inj2 = FaultInjector(site="pre_save", mode="raise", once_file=marker)
        inj2.maybe_fire("pre_save")           # marker present: disarmed

    def test_env_protocol(self, monkeypatch):
        monkeypatch.setenv("DSTPU_FAULT_SITE", "collective")
        monkeypatch.setenv("DSTPU_FAULT_MODE", "raise")
        monkeypatch.setenv("DSTPU_FAULT_TIMES", "7")
        inj = FaultInjector.from_env()
        assert inj.site == "collective" and inj.mode == "raise"
        assert inj.times == 7


# ------------------------------------------------------------------------- #
# self-healing checkpoints
# ------------------------------------------------------------------------- #

class TestSelfHealingCheckpoints:
    def test_mid_save_crash_resumes_previous_tag(self, tmp_path):
        """THE acceptance bar: torn mid-save -> restart resumes the newest
        valid tag with identical global_steps and optimizer state."""
        e = _engine()
        e.train_batch(_batch(e, 0))
        e.train_batch(_batch(e, 1))
        e.save_checkpoint(str(tmp_path))                  # global_step2
        params_at_2 = _params_snapshot(e)
        opt_at_2 = _opt_snapshot(e)

        e.train_batch(_batch(e, 2))                       # -> step 3
        set_fault_injector(FaultInjector(site="mid_save", mode="raise"))
        with pytest.raises(InjectedFault):
            e.save_checkpoint(str(tmp_path))              # torn global_step3
        set_fault_injector(None)

        # the crash left a torn tmp dir, an intact previous tag, and an
        # untouched latest pointer
        tmps = [d for d in os.listdir(tmp_path) if ".tmp-" in d]
        assert tmps, "torn tmp dir should remain for forensics"
        assert (tmp_path / LATEST_FILE).read_text() == "global_step2"
        assert not (tmp_path / "global_step3").exists()

        e2 = _engine()
        path, _ = e2.load_checkpoint(str(tmp_path))
        assert path is not None and path.endswith("global_step2")
        assert e2.global_steps == 2
        for a, b in zip(params_at_2, _params_snapshot(e2)):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(opt_at_2, _opt_snapshot(e2)):
            np.testing.assert_array_equal(a, b)
        # training continues
        assert np.isfinite(float(e2.train_batch(_batch(e2, 2))))

    def test_pre_save_crash_leaves_store_untouched(self, tmp_path):
        e = _engine()
        e.train_batch(_batch(e, 0))
        e.save_checkpoint(str(tmp_path))
        before = sorted(os.listdir(tmp_path))
        e.train_batch(_batch(e, 1))
        set_fault_injector(FaultInjector(site="pre_save", mode="raise"))
        with pytest.raises(InjectedFault):
            e.save_checkpoint(str(tmp_path))
        set_fault_injector(None)
        assert sorted(os.listdir(tmp_path)) == before

    def test_post_save_pre_latest_crash_keeps_old_pointer(self, tmp_path):
        """Crash after the tag is durable but before publish: the save is
        UNCOMMITTED — resume comes from the previous latest."""
        e = _engine()
        e.train_batch(_batch(e, 0))
        e.save_checkpoint(str(tmp_path))                  # global_step1
        e.train_batch(_batch(e, 1))
        set_fault_injector(FaultInjector(site="post_save_pre_latest",
                                         mode="raise"))
        with pytest.raises(InjectedFault):
            e.save_checkpoint(str(tmp_path))
        set_fault_injector(None)
        # tag 2 is on disk and VALID, but latest still commits tag 1
        ok, _ = validate_checkpoint_dir(str(tmp_path / "global_step2"))
        assert ok
        assert (tmp_path / LATEST_FILE).read_text() == "global_step1"
        e2 = _engine()
        path, _ = e2.load_checkpoint(str(tmp_path))
        assert path.endswith("global_step1") and e2.global_steps == 1

    def test_checksum_mismatch_falls_back_and_quarantines(self, tmp_path):
        e = _engine()
        e.train_batch(_batch(e, 0))
        e.save_checkpoint(str(tmp_path))                  # global_step1
        params_at_1 = _params_snapshot(e)
        e.train_batch(_batch(e, 1))
        e.save_checkpoint(str(tmp_path))                  # global_step2
        # bit-rot the newest tag's state file
        state = tmp_path / "global_step2" / STATE_FILE
        blob = bytearray(state.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        state.write_bytes(bytes(blob))

        e2 = _engine()
        path, _ = e2.load_checkpoint(str(tmp_path))
        assert path.endswith("global_step1")
        assert e2.global_steps == 1
        for a, b in zip(params_at_1, _params_snapshot(e2)):
            np.testing.assert_array_equal(a, b)
        # the corrupt tag is quarantined and the pointer healed
        assert not (tmp_path / "global_step2").exists()
        assert any(QUARANTINE_SUFFIX in d for d in os.listdir(tmp_path))
        assert (tmp_path / LATEST_FILE).read_text() == "global_step1"

    def test_explicit_corrupt_tag_raises(self, tmp_path):
        e = _engine()
        e.train_batch(_batch(e, 0))
        e.save_checkpoint(str(tmp_path))
        state = tmp_path / "global_step1" / STATE_FILE
        state.write_bytes(b"garbage")
        e2 = _engine()
        with pytest.raises(ValueError, match="failed validation"):
            e2.load_checkpoint(str(tmp_path), tag="global_step1")

    def test_publish_refuses_invalid_tag(self, tmp_path):
        os.makedirs(tmp_path / "broken_tag")
        with pytest.raises(RuntimeError, match="refusing to publish"):
            publish_latest(str(tmp_path), "broken_tag")
        assert not (tmp_path / LATEST_FILE).exists()

    def test_save_retries_transient_io_errors(self, tmp_path, monkeypatch):
        e = _engine()
        e.train_batch(_batch(e, 0))
        real_savez = np.savez
        fails = {"n": 2}

        def flaky_savez(*a, **kw):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise OSError("transient write blip")
            return real_savez(*a, **kw)

        monkeypatch.setattr(np, "savez", flaky_savez)
        path = e.save_checkpoint(str(tmp_path))
        assert fails["n"] == 0
        ok, reason = validate_checkpoint_dir(path)
        assert ok, reason

    def test_save_retry_budget_bounded(self, tmp_path, monkeypatch):
        e = _engine()
        e.train_batch(_batch(e, 0))
        calls = {"n": 0}

        def dead_savez(*a, **kw):
            calls["n"] += 1
            raise OSError("disk on fire")

        monkeypatch.setattr(np, "savez", dead_savez)
        with pytest.raises(OSError):
            e.save_checkpoint(str(tmp_path))
        assert calls["n"] == e.config.checkpoint.save_retries + 1

    def test_find_valid_tag_ordering(self, tmp_path):
        e = _engine()
        for i in range(3):
            e.train_batch(_batch(e, i))
            e.save_checkpoint(str(tmp_path))
        assert find_valid_tag(str(tmp_path)) == "global_step3"
        # prefer the pointer when it validates, even if older
        assert find_valid_tag(str(tmp_path),
                              preferred="global_step1") == "global_step1"


# ------------------------------------------------------------------------- #
# engine fault sites
# ------------------------------------------------------------------------- #

class TestEngineFaultSites:
    def test_step_site_fires_at_step_n(self):
        e = _engine()
        e.train_batch(_batch(e, 0))
        set_fault_injector(FaultInjector(site="step", mode="raise",
                                         at_step=2))
        assert np.isfinite(float(e.train_batch(_batch(e, 1))))  # step 1->2
        with pytest.raises(InjectedFault):
            e.train_batch(_batch(e, 2))                          # step 2: fire


# ------------------------------------------------------------------------- #
# preemption grace (in-process + end-to-end through the elastic agent)
# ------------------------------------------------------------------------- #

class TestPreemption:
    def _preemptible_engine(self, save_dir):
        cfg_model = GPT2Config.tiny(dtype=jnp.float32)
        model, init_fn, loss_fn = make_model(cfg_model)
        params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=17)
        engine, _, _, _ = dstpu.initialize(
            loss_fn=loss_fn, params=params, config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
                "steps_per_print": 1000,
                "resilience": {"preemption": {"enabled": True,
                                              "save_dir": str(save_dir)}},
            })
        return engine

    def test_request_triggers_urgent_save_and_elastic_exit(self, tmp_path):
        from deepspeed_tpu.elasticity.elastic_agent import (
            MEMBERSHIP_CHANGE_EXIT)
        e = self._preemptible_engine(tmp_path / "ck")
        try:
            e.train_batch(_batch(e, 0))
            e.preemption.request()
            with pytest.raises(SystemExit) as exc:
                e.train_batch(_batch(e, 1))
            assert exc.value.code == MEMBERSHIP_CHANGE_EXIT
        finally:
            if e.preemption is not None:
                e.preemption.uninstall()
        # the urgent checkpoint covers the step that was just completed
        e2 = _engine()
        path, _ = e2.load_checkpoint(str(tmp_path / "ck"))
        assert path is not None and e2.global_steps == 2

    def test_real_sigterm_sets_flag(self, tmp_path):
        e = self._preemptible_engine(tmp_path / "ck")
        try:
            assert not e.preemption.preempted
            os.kill(os.getpid(), signal.SIGTERM)
            assert e.preemption.wait(timeout=5.0)
        finally:
            e.preemption.uninstall()

    def test_uninstall_restores_handlers(self, tmp_path):
        before = signal.getsignal(signal.SIGTERM)
        e = self._preemptible_engine(tmp_path / "ck")
        assert signal.getsignal(signal.SIGTERM) != before
        e.preemption.uninstall()
        assert signal.getsignal(signal.SIGTERM) == before


WORKER_SCRIPT = r"""
import json, os, signal, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax, jax.numpy as jnp, numpy as np
import deepspeed_tpu as dstpu
from deepspeed_tpu.models.gpt2 import GPT2Config, make_model

save_dir = os.environ["WK_SAVE_DIR"]
progress = os.environ["WK_PROGRESS_FILE"]
stop_at = int(os.environ.get("WK_STEPS", "6"))
sigterm_step = int(os.environ.get("WK_SELF_SIGTERM_STEP", "-1"))

cfg = GPT2Config.tiny(dtype=jnp.float32)
_, init_fn, loss_fn = make_model(cfg)
params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=17)
engine, _, _, _ = dstpu.initialize(
    loss_fn=loss_fn, params=params, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "steps_per_print": 1000,
        "resilience": {"preemption": {"enabled": True,
                                      "save_dir": save_dir}},
    })
engine.load_checkpoint(save_dir)
while engine.global_steps < stop_at:
    rng = np.random.RandomState(engine.global_steps)
    batch = {"tokens": jnp.asarray(
        rng.randint(0, 512, size=(engine.config.train_batch_size, 18)),
        jnp.int32)}
    if engine.global_steps + 1 == sigterm_step:
        os.kill(os.getpid(), signal.SIGTERM)   # delivered before this step
    engine.train_batch(batch)                  # step boundary handles it
    with open(progress, "w") as f:
        json.dump({"global_steps": engine.global_steps}, f)
sys.exit(0)
"""


class TestElasticPreemptionEndToEnd:
    def _env(self, tmp_path, **extra):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)          # 1 CPU device: fastest
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(dstpu.__file__)))
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": repo_root + os.pathsep + env.get("PYTHONPATH", ""),
            "WK_SAVE_DIR": str(tmp_path / "ck"),
            "WK_PROGRESS_FILE": str(tmp_path / "progress.json"),
        })
        env.update({k: str(v) for k, v in extra.items()})
        return env

    def test_sigterm_final_checkpoint_and_clean_resume(self, tmp_path):
        """Worker preempted mid-run checkpoints, exits 99; the elastic
        agent restarts it; the resumed run continues from the SAME
        global_steps and finishes — zero lost steps."""
        from deepspeed_tpu.elasticity import run_elastic
        script = tmp_path / "worker.py"
        script.write_text(WORKER_SCRIPT)
        ledger_path = str(tmp_path / "ledger.json")
        rc = run_elastic(
            [sys.executable, str(script)],
            {"max_train_batch_size": 2000, "micro_batch_sizes": [2],
             "min_gpus": 1, "max_gpus": 8, "version": 0.1},
            max_restarts=3, min_restart_interval_s=0.0,
            backoff_base_s=0.01, ledger_path=ledger_path,
            env=self._env(tmp_path, WK_SELF_SIGTERM_STEP=3, WK_STEPS=6),
        )
        assert rc == 0
        progress = json.loads((tmp_path / "progress.json").read_text())
        assert progress["global_steps"] == 6
        events = json.loads(open(ledger_path).read())["events"]
        kinds = [ev["event"] for ev in events]
        assert "restart" in kinds and "success" in kinds
        restart = next(ev for ev in events if ev["event"] == "restart")
        assert restart["membership_change"] is True and restart["rc"] == 99
        # the preemption checkpoint landed BEFORE the restart: step 3 (the
        # step in flight when SIGTERM arrived) completed and saved — it is
        # the worker's ONLY checkpoint, and the resumed run continued from
        # exactly there (3 -> 6 with zero lost or repeated steps)
        from deepspeed_tpu.checkpoint.engine_checkpoint import find_valid_tag
        assert find_valid_tag(str(tmp_path / "ck")) == "global_step3"

    def test_crash_loop_budget_stops_restarts(self, tmp_path):
        from deepspeed_tpu.elasticity import run_elastic
        script = tmp_path / "crash.py"
        script.write_text("import sys; sys.exit(1)\n")
        ledger_path = str(tmp_path / "ledger.json")
        t0 = time.time()
        rc = run_elastic(
            [sys.executable, str(script)],
            {"max_train_batch_size": 2000, "micro_batch_sizes": [2],
             "min_gpus": 1, "max_gpus": 8, "version": 0.1},
            max_restarts=100, min_restart_interval_s=0.0,
            backoff_base_s=0.01, crash_loop_budget=3,
            ledger_path=ledger_path)
        assert rc == 1
        assert time.time() - t0 < 30
        events = json.loads(open(ledger_path).read())["events"]
        giveup = [ev for ev in events if ev["event"] == "giveup"]
        assert giveup and giveup[0]["reason"] == "crash_loop"
        # budget of 3 fast failures: far fewer than max_restarts launches
        assert sum(ev["event"] == "launch" for ev in events) == 3


# ------------------------------------------------------------------------- #
# step watchdog
# ------------------------------------------------------------------------- #

class TestStepWatchdog:
    def _dog(self, **kw):
        kw.setdefault("check_interval_s", 3600)   # tick manually
        kw.setdefault("min_median_samples", 2)
        kw.setdefault("min_stall_s", 0.01)
        kw.setdefault("stall_factor", 2.0)
        return StepWatchdog(**kw)

    def test_stall_diagnosis_names_last_collective(self):
        from deepspeed_tpu.comm.comms_logging import note_collective
        wd = self._dog()
        try:
            for i in range(3):
                wd.step_start(i)
                wd.step_end(i)
            note_collective("all_reduce", 4096, 8, log_name="grad_sync")
            wd.step_start(3)
            wd.phase("compiled_step")
            time.sleep(0.05)
            diag = wd.check_once()
            assert diag is not None
            assert diag["step"] == 3
            assert diag["last_phase"] == "compiled_step"
            assert diag["last_collective"]["op"] == "all_reduce"
            assert diag["last_collective"]["log_name"] == "grad_sync"
            # one report per step, not one per tick
            assert wd.check_once() is None
        finally:
            wd.stop()

    def test_no_stall_within_budget(self):
        wd = self._dog(min_stall_s=60.0)
        try:
            for i in range(3):
                wd.step_start(i)
                wd.step_end(i)
            wd.step_start(3)
            assert wd.check_once() is None
        finally:
            wd.stop()

    def test_idle_engine_never_stalls(self):
        wd = self._dog()
        try:
            for i in range(3):
                wd.step_start(i)
                wd.step_end(i)
            time.sleep(0.05)
            assert wd.check_once() is None     # not in a step
        finally:
            wd.stop()

    def test_heartbeat_file_written(self, tmp_path):
        hb = str(tmp_path / "hb.json")
        wd = self._dog(heartbeat_file=hb)
        try:
            wd.step_start(0)
            wd._heartbeat()
            blob = json.loads(open(hb).read())
            assert blob["in_step"] == 0
            assert blob["last_phase"] == "step"
        finally:
            wd.stop()

    def test_engine_wires_watchdog_from_config(self):
        cfg_model = GPT2Config.tiny(dtype=jnp.float32)
        model, init_fn, loss_fn = make_model(cfg_model)
        params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=17)
        engine, _, _, _ = dstpu.initialize(
            loss_fn=loss_fn, params=params, config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
                "resilience": {"watchdog": {"enabled": True,
                                            "check_interval_s": 3600}},
            })
        try:
            engine.train_batch(_batch(engine, 0))
            engine.train_batch(_batch(engine, 1))
            assert len(engine._watchdog._durations) == 2
            assert engine._watchdog._step is None      # idle between steps
        finally:
            engine._watchdog.stop()


# ------------------------------------------------------------------------- #
# restart ledger
# ------------------------------------------------------------------------- #

class TestRestartLedger:
    def test_append_and_reload(self, tmp_path):
        path = str(tmp_path / "ledger.json")
        led = RestartLedger(path)
        led.record("launch", pid=1)
        led.record("restart", rc=99)
        led2 = RestartLedger(path)            # survives supervisor restart
        assert [ev["event"] for ev in led2.events] == ["launch", "restart"]

    def test_pathless_ledger_in_memory(self):
        led = RestartLedger(None)
        led.record("launch")
        assert len(led.events) == 1


# ------------------------------------------------------------------------- #
# the CI fault drill (subset: keep tier-1 fast; bin/dstpu_faultdrill runs
# every site)
# ------------------------------------------------------------------------- #

class TestFaultDrill:
    def test_drill_recovers_torn_save(self, tmp_path):
        from deepspeed_tpu.resilience.faultdrill import main
        rc = main(["--sites", "mid_save,post_save_pre_latest",
                   "--workdir", str(tmp_path)])
        assert rc == 0

    @pytest.mark.slow
    def test_serve_drill_hard_crash_and_sigterm(self, tmp_path):
        # one hard-crash site (journal recovery) + the cooperative
        # SIGTERM drain (manifest recovery); bin/dstpu_faultdrill
        # --mode serve runs every serve site in CI (tools/tpu_round11.sh)
        from deepspeed_tpu.resilience.faultdrill import main
        rc = main(["--mode", "serve", "--sites", "mid_commit,sigterm",
                   "--workdir", str(tmp_path)])
        assert rc == 0

    def test_sites_cover_the_documented_set(self):
        from deepspeed_tpu.resilience import (DISAGG_FAULT_SITE,
                                              SERVE_FAULT_SITES,
                                              TRAIN_FAULT_SITES)
        assert TRAIN_FAULT_SITES == (
            "pre_save", "mid_save", "post_save_pre_latest", "collective",
            "step")
        assert SERVE_FAULT_SITES == (
            "pre_dispatch", "mid_commit", "during_prefill_chunk",
            "during_cow_copy")
        assert DISAGG_FAULT_SITE == "during_handoff_gather"
        assert FAULT_SITES == (TRAIN_FAULT_SITES + SERVE_FAULT_SITES
                               + (DISAGG_FAULT_SITE,))
