"""Tests for deepspeed_tpu.comm — facade collectives inside shard_map over
the 8-device virtual mesh (the analogue of the reference's
``tests/unit/comm/test_dist.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.comm.comms_logging import calc_bw_log, get_comms_logger


@pytest.fixture
def mesh(devices8):
    return Mesh(np.asarray(devices8), ("data",))


def test_all_reduce_sum(mesh):
    x = jnp.arange(8.0)

    f = shard_map(lambda v: comm.all_reduce(v, "sum", axis_name="data"),
                  mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    out = f(x)
    np.testing.assert_allclose(out, np.full(8, x.sum()))


def test_all_reduce_avg_and_max(mesh):
    x = jnp.arange(8.0)
    favg = shard_map(lambda v: comm.all_reduce(v, "avg", axis_name="data"),
                     mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    np.testing.assert_allclose(favg(x), np.full(8, x.mean()))
    fmax = shard_map(lambda v: comm.all_reduce(v, "max", axis_name="data"),
                     mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    np.testing.assert_allclose(fmax(x), np.full(8, 7.0))


def test_all_gather_reduce_scatter_roundtrip(mesh):
    x = jnp.arange(16.0).reshape(8, 2)

    def body(v):  # v: [1, 2] per rank
        g = comm.all_gather(v, axis_name="data", axis=0)   # [8, 2]
        return comm.reduce_scatter(g, axis_name="data", axis=0)  # [1, 2]

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    out = f(x)
    # reduce_scatter(all_gather(x)) = 8 * x
    np.testing.assert_allclose(out, 8.0 * np.asarray(x))


def test_all_to_all_single(mesh):
    # each rank holds a row of 8 values; a2a transposes rank/col blocks
    x = jnp.arange(64.0).reshape(8, 8)

    def body(v):  # [1, 8]
        return comm.all_to_all_single(v[0], axis_name="seq", split_axis=0,
                                      concat_axis=0)[None]

    m = Mesh(np.asarray(jax.devices()), ("seq",))
    f = shard_map(body, mesh=m, in_specs=P("seq"), out_specs=P("seq"))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.asarray(x).reshape(8, 8).T)


def test_broadcast(mesh):
    x = jnp.arange(8.0)
    f = shard_map(lambda v: comm.broadcast(v, src=3, axis_name="data"),
                  mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    np.testing.assert_allclose(f(x), np.full(8, 3.0))


def test_ppermute_ring(mesh):
    x = jnp.arange(8.0)
    perm = [(i, (i + 1) % 8) for i in range(8)]
    f = shard_map(lambda v: comm.ppermute(v, perm, axis_name="data"),
                  mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    np.testing.assert_allclose(f(x), np.roll(np.arange(8.0), 1))


def test_comms_logger_records_and_summarizes(mesh):
    lg = get_comms_logger()
    lg.reset()
    lg.configure(enabled=True, prof_all=True)
    x = jnp.arange(8.0)
    f = shard_map(lambda v: comm.all_reduce(v, "sum", axis_name="data"),
                  mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    f(x)  # trace records volume
    assert "all_reduce" in lg.comms_dict
    summary = comm.log_summary()
    assert "all_reduce" in summary
    lg.configure(enabled=False)
    lg.reset()


def test_calc_bw_log_math():
    # allreduce: 2x size, bus = algo*(n-1)/n
    algo, bus = calc_bw_log("all_reduce", 1 << 30, 1.0, 8)
    assert algo == pytest.approx(2 * (1 << 30) / 1e9)
    assert bus == pytest.approx(algo * 7 / 8)
    # allgather: n x size
    algo, bus = calc_bw_log("all_gather", 1 << 20, 0.5, 4)
    assert algo == pytest.approx(4 * (1 << 20) / 0.5 / 1e9)
    # p2p
    algo, bus = calc_bw_log("ppermute", 1000, 1.0, 8)
    assert algo == bus == pytest.approx(1000 / 1e9)


def test_init_distributed_single_host_noop():
    comm.init_distributed()
    assert comm.is_initialized()
    assert comm.get_world_size() == 8
    assert comm.get_rank() == 0
    assert comm.get_local_rank() == 0


def test_mpi_discovery_env(monkeypatch):
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "16")
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
    monkeypatch.setenv("MASTER_PORT", "1234")
    found = comm.mpi_discovery()
    assert found == {"process_id": 3, "num_processes": 16,
                     "coordinator_address": "10.0.0.1",
                     "coordinator_port": 1234}
