"""1-bit optimizers + error-compensated compressed allreduce.

Reference parity: tests/onebit/ and runtime/fp16/onebit/{adam,lamb,zoadam}.py
(warmup at full precision, then sign-compressed communication with
worker/server error feedback; frozen second moment after freeze_step)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu as dstpu
from deepspeed_tpu.models.gpt2 import GPT2Config, make_model
from deepspeed_tpu.ops.optimizers import build_optimizer, is_onebit
from deepspeed_tpu.runtime.compressed_grads import (
    chunk_size, onebit_allreduce, pack_signs, unpack_signs)
from deepspeed_tpu.runtime.zero.quantized_collectives import shard_map


class TestPackedSigns:
    def test_roundtrip(self):
        s = jax.random.bernoulli(jax.random.PRNGKey(0), 0.5, (3, 5, 32))
        out = unpack_signs(pack_signs(s))
        np.testing.assert_array_equal(np.asarray(out),
                                      np.where(np.asarray(s), 1.0, -1.0))

    def test_chunk_size(self):
        assert chunk_size(64, 8) == 8
        assert chunk_size(65, 8) == 16   # ceil(65/8)=9 -> byte-rounded 16
        assert chunk_size(1, 8) == 8


class TestOnebitAllreduce:
    def test_error_feedback_unbiased(self, devices8):
        mesh = Mesh(np.array(devices8).reshape(8), axis_names=("data",))
        W, k = 8, 16

        def local(x, w, s):
            out, nw, ns = onebit_allreduce(x[0], w[0], s[0], ("data",), W)
            return out, nw[None], ns[None]

        f = shard_map(local, mesh,
                      in_specs=(P("data"), P("data"), P("data")),
                      out_specs=(P(), P("data"), P("data")),
                      axis_names=("data",))
        w_ = jnp.zeros((W, W, k))
        s_ = jnp.zeros((W, k))
        acc_1bit = np.zeros(W * k)
        acc_true = np.zeros(W * k)
        for i in range(30):
            xi = jax.random.normal(jax.random.PRNGKey(i), (W, W * k)) + 0.3
            out, w_, s_ = f(xi, w_, s_)
            acc_1bit += np.asarray(out)
            acc_true += np.asarray(xi.mean(0))
        rel = np.abs(acc_1bit - acc_true).mean() / np.abs(acc_true).mean()
        assert rel < 0.2, f"error feedback failed to bound drift: {rel}"


class TestOnebitOptimizers:
    def test_frozen_variance_after_freeze(self):
        opt = build_optimizer("OneBitAdam", {"lr": 1e-2, "freeze_step": 3})
        params = {"w": jnp.ones((4,))}
        state = opt.init(params)
        gs = [{"w": jnp.full((4,), float(i + 1))} for i in range(6)]
        nus = []
        for g in gs:
            _, state = opt.update(g, state, params)
            nus.append(np.asarray(state.nu["w"]).copy())
        assert not np.allclose(nus[0], nus[2])      # warmup: nu moves
        np.testing.assert_array_equal(nus[3], nus[4])  # frozen
        np.testing.assert_array_equal(nus[4], nus[5])

    def test_zeroone_refresh_interval(self):
        opt = build_optimizer(
            "ZeroOneAdam", {"lr": 1e-2, "freeze_step": 2,
                            "var_update_scaler": 4})
        params = {"w": jnp.ones((4,))}
        state = opt.init(params)
        nus = []
        for i in range(9):
            g = {"w": jnp.full((4,), float(i + 1))}
            _, state = opt.update(g, state, params)
            nus.append(np.asarray(state.nu["w"]).copy())
        # frozen right after warmup (count 3 keeps count-2's nu)
        np.testing.assert_array_equal(nus[1], nus[2])
        # count 4 and 8 refresh the variance
        assert not np.allclose(nus[2], nus[3])
        np.testing.assert_array_equal(nus[4], nus[5])
        assert not np.allclose(nus[6], nus[7])

    def test_onebit_lamb_runs(self):
        opt = build_optimizer("OneBitLamb", {"lr": 1e-2, "freeze_step": 2})
        params = {"w": jnp.ones((4, 4))}
        state = opt.init(params)
        for i in range(4):
            upd, state = opt.update({"w": jnp.ones((4, 4))}, state, params)
        assert np.isfinite(np.asarray(upd["w"])).all()

    def test_is_onebit(self):
        assert is_onebit("OneBitAdam") and is_onebit("zerooneadam")
        assert not is_onebit("AdamW")


class TestOnebitEngine:
    def _run(self, opt_type, steps=24, freeze_step=8):
        cfg = GPT2Config.tiny(dtype=jnp.float32)
        model, init_fn, loss_fn = make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=16)
        engine, _, _, _ = dstpu.initialize(
            loss_fn=loss_fn, params=params, config={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": opt_type,
                              "params": {"lr": 3e-3,
                                         "freeze_step": freeze_step}},
                "zero_optimization": {"stage": 1},
            })
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(steps):
            starts = rng.integers(0, 512, size=(32,))
            seq = (starts[:, None] + np.arange(17)[None, :]) % 512
            losses.append(float(engine.train_batch(
                {"tokens": jnp.asarray(seq, jnp.int32)})))
        return losses

    @pytest.mark.parametrize("opt", ["OneBitAdam", "OneBitLamb",
                                     "ZeroOneAdam"])
    def test_training_through_freeze_boundary(self, devices8, opt):
        losses = self._run(opt)
        assert all(np.isfinite(l) for l in losses)
        # learns through warmup AND keeps improving in the compressed stage
        assert losses[7] < losses[0]
        assert min(losses[8:]) < losses[7]
