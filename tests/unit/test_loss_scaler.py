"""Dynamic loss scaler semantics — mirrors reference runtime/fp16/loss_scaler.py
behavior: hysteresis consumption, halving, window growth, restore rules."""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.config.config import FP16Config
from deepspeed_tpu.runtime import loss_scaler as ls

CFG = FP16Config(enabled=True, initial_scale_power=8, loss_scale_window=4,
                 hysteresis=2, min_loss_scale=1.0)


def _run(cfg, pattern):
    """pattern: string of 'c' (clean) / 'o' (overflow). Returns scale history."""
    state = ls.init_state(cfg)
    scales = []
    for ch in pattern:
        state = ls.update_state(state, jnp.asarray(ch == "c"), cfg)
        scales.append(float(state.scale))
    return scales, state


def test_initial_scale():
    state = ls.init_state(CFG)
    assert float(state.scale) == 2.0 ** 8


def test_hysteresis_consumed_then_halve():
    # first overflow: consume hysteresis (scale unchanged); second: halve
    scales, _ = _run(CFG, "oo")
    assert scales == [256.0, 128.0]


def test_consecutive_overflows_keep_halving():
    scales, _ = _run(CFG, "oooo")
    assert scales == [256.0, 128.0, 64.0, 32.0]


def test_nonconsecutive_overflows_still_halve():
    """consecutive_hysteresis=False: clean steps do NOT restore hysteresis,
    so alternating overflow/clean eventually halves (reference semantics)."""
    scales, _ = _run(CFG, "ococ")
    # o: hyst 2->1; c: no restore; o: hyst==1 -> halve
    assert scales[-1] < 256.0


def test_consecutive_hysteresis_true_restores():
    cfg = FP16Config(enabled=True, initial_scale_power=8, loss_scale_window=100,
                     hysteresis=2, consecutive_hysteresis=True)
    scales, _ = _run(cfg, "ococococ")
    # every clean step restores hysteresis to 2, so scale never halves
    assert scales[-1] == 256.0


def test_growth_after_window():
    scales, _ = _run(CFG, "cccc")
    assert scales == [256.0, 256.0, 256.0, 512.0]


def test_growth_resets_tracker():
    scales, _ = _run(CFG, "cccccccc")
    assert scales[-1] == 1024.0


def test_overflow_resets_growth_tracker():
    # 3 clean, 1 overflow, 3 clean -> no growth yet (tracker reset)
    scales, _ = _run(CFG, "cccoccc")
    assert scales[-1] == 256.0


def test_min_scale_floor():
    cfg = FP16Config(enabled=True, initial_scale_power=2, hysteresis=1,
                     min_loss_scale=2.0)
    scales, _ = _run(cfg, "ooooo")
    assert scales[-1] == 2.0


def test_static_scale_never_changes():
    cfg = FP16Config(enabled=True, loss_scale=128.0)
    scales, state = _run(cfg, "ococcc")
    assert all(s == 128.0 for s in scales)
    assert int(state.overflows) == 2


def test_overflow_counter():
    _, state = _run(CFG, "ooccco")
    assert int(state.overflows) == 3
