"""Serve-side resilience tests (ISSUE 7): preemption-safe drain/replay
for the v2 ragged engine.

The parity oracle for the whole layer: a kill (injected fault or
cooperative drain) at ANY pipeline stage, followed by manifest/journal
replay on a fresh or survivor engine, must yield token streams identical
to the uninterrupted greedy run — with zero leaked KV blocks and exact
prefix-cache refcounts. Heavier combos (full kill grid, llama, tp2) ride
the full/slow tier; ``bin/dstpu_faultdrill --mode serve`` drills the
hard-crash (``os._exit``) variants in subprocesses."""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (
    EngineDrainingError,
    InferenceEngineV2,
    RaggedInferenceConfig,
    ServeStepError,
    load_replay_state,
    manifest_from_journal,
)
from deepspeed_tpu.inference.v2.drain import load_manifest, write_manifest
from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config
from deepspeed_tpu.resilience.fault_injection import (
    SERVE_FAULT_SITES,
    FaultInjector,
    InjectedFault,
    set_fault_injector,
)

# the standard workload: 3 requests sharing a 10-token system preamble
# (block_size 4 -> two full shared blocks + a partial-tail CoW copy on
# every later request) with unique 5-token tails; serve N_TOK tokens each
UIDS = (0, 1, 2)
N_TOK = 8
_rng = np.random.default_rng(55)
_SHARED = _rng.integers(1, 96, 10).tolist()
PROMPTS = tuple(_SHARED + _rng.integers(1, 96, 5).tolist() for _ in UIDS)

_CACHE = {}


def _gpt2():
    if "gpt2" not in _CACHE:
        mcfg = GPT2Config(vocab_size=96, max_seq_len=128, num_layers=2,
                          num_heads=2, hidden_size=32, dtype=jnp.float32)
        params = GPT2(mcfg).init(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))["params"]
        _CACHE["gpt2"] = (mcfg, params)
    return _CACHE["gpt2"]


def _cfg(prefix=True, depth=2, **kw):
    base = dict(max_seqs=4, chunk_size=8, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, dtype="float32",
                attention_impl="dense", decode_loop_steps=0,
                serve_pipeline_depth=depth, prefix_cache=prefix)
    base.update(kw)
    return RaggedInferenceConfig(**base)


def _serve(eng, n=N_TOK, uids=UIDS, prompts=PROMPTS, rounds_of=2):
    """Drive the serve loop the way a serving layer does: admit each
    request (prefix matching + CoW fire on the later ones), then decode
    all live sequences in small pipelined rounds. Sequences stay LIVE on
    return — the drain tests snapshot them mid-service."""
    toks = {}
    for u, p in zip(uids, prompts):
        r = eng.put([u], [list(p)], _greedy=True)
        if u in r:
            toks[u] = [int(r[u])]
    while True:
        live = [u for u in toks
                if len(toks[u]) < n and u not in eng.rejections
                and u in eng.state.sequences]
        if not live:
            return toks
        k = min(rounds_of, n - min(len(toks[u]) for u in live))
        outs = eng.decode_pipelined(live, [toks[u][-1] for u in live], k)
        got = False
        for u in live:
            if outs[u]:
                got = True
            toks[u].extend(outs[u][:n - len(toks[u])])
        if not got:          # draining / everything shed: no progress
            return toks


@pytest.fixture(scope="module")
def oracle():
    """The uninterrupted greedy stream — computed once on the sync
    (depth-0, cache-off) engine; every interrupted-then-replayed run
    must reproduce it token for token."""
    mcfg, params = _gpt2()
    eng = InferenceEngineV2(mcfg, params, _cfg(prefix=False, depth=0))
    return _serve(eng)


def _assert_released(eng, manifest):
    """No leaked state after a drain: every block back to the allocator
    (or the cache's refcount-0 evictable set, which counts as free
    capacity), refcounts exactly zero, sequence table empty."""
    assert manifest["pool"]["fully_recovered"], manifest["pool"]
    assert eng.free_blocks == eng.config.num_blocks
    assert not eng.state.sequences
    if eng._prefix is not None:
        eng._prefix.check_invariants()
        assert eng._prefix.evictable_blocks == eng._prefix.cached_blocks


def _replay_and_finish(manifest, cfg, n=N_TOK, model=None):
    """Fresh-engine recovery: re-put() every manifest sequence and decode
    each to ``n`` total tokens. Returns (engine, {uid: tokens})."""
    mcfg, params = model if model is not None else _gpt2()
    eng = InferenceEngineV2(mcfg, params, cfg)
    out = eng.replay(manifest)
    toks = {int(s["uid"]): list(s["generated"])
            for s in manifest["sequences"]}
    for u in list(toks):
        # a kill after a request finished its budget leaves a full
        # generated list; replay's next token is then beyond the
        # comparison window
        if u in out and len(toks[u]) < n:
            toks[u].append(int(out[u]))
    while True:
        short = [u for u in toks if len(toks[u]) < n]
        if not short:
            return eng, toks
        outs = eng.decode_pipelined(short, [toks[u][-1] for u in short],
                                    [n - len(toks[u]) for u in short])
        for u in short:
            toks[u].extend(outs[u][:n - len(toks[u])])


class TestDrainReplay:
    """Cooperative drain (the SIGTERM path, minus the signal): stop
    admitting, unwind the pipeline, manifest, replay elsewhere."""

    def test_drain_replay_parity_and_release(self, oracle, tmp_path):
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg())
        partial = _serve(eng, n=4)
        eng.request_drain()
        # draining: FRESH admissions are refused with a structured
        # rejection; a continuation of a live sequence is NOT rejected
        # (it rides the manifest — a record would double-route it), and
        # replay() on this replica is an error
        assert eng.put([9], [[1, 2, 3]]) == {}
        assert eng.rejections[9]["reason"] == "draining"
        assert eng.put([0], [[partial[0][-1]]]) == {}
        assert 0 not in eng.rejections
        with pytest.raises(EngineDrainingError):
            eng.replay({"sequences": []})
        path = str(tmp_path / "m.json")
        m = eng.drain(path)
        _assert_released(eng, m)
        # atomic publish round-trips, and the manifest carries exactly
        # the committed partial streams plus the scheduler snapshot
        m2 = load_manifest(path)
        assert [s["uid"] for s in m2["sequences"]] == list(UIDS)
        for s in m2["sequences"]:
            assert s["prompt"] == list(PROMPTS[s["uid"]])
            assert s["generated"] == partial[s["uid"]]
            assert s["scheduler"]["seen_tokens"] > 0
        # replay on a fresh engine: token-identical continuation
        eng2, toks = _replay_and_finish(m2, _cfg())
        assert toks == oracle
        # the replayed sequences stay live with prompt/generated split
        # restored: a LATER drain is cumulative
        m3 = eng2.drain()
        for s in m3["sequences"]:
            assert s["prompt"] == list(PROMPTS[s["uid"]])
            assert s["generated"] == oracle[s["uid"]]
        _assert_released(eng2, m3)

    @pytest.mark.slow
    def test_drain_replay_parity_prefix_off(self, oracle):
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg(prefix=False))
        _serve(eng, n=3)
        m = eng.drain()
        _assert_released(eng, m)
        _, toks = _replay_and_finish(m, _cfg(prefix=False))
        assert toks == oracle

    @pytest.mark.slow
    def test_survivor_replay_is_mostly_prefix_hits(self, oracle):
        # a SURVIVOR engine that already served the shared-prefix
        # workload replays the manifest with most re-prefill served from
        # its cache (the ROADMAP's cheap-recovery claim)
        mcfg, params = _gpt2()
        dead = InferenceEngineV2(mcfg, params, _cfg())
        _serve(dead, n=4)
        m = dead.drain()
        surv = InferenceEngineV2(mcfg, params, _cfg())
        warm = _serve(surv, uids=(7, 8), prompts=(
            _SHARED + [3, 1, 4, 1, 5], _SHARED + [9, 2, 6, 5, 3]), n=2)
        assert set(warm) == {7, 8}
        st0 = surv.prefix_stats
        out = surv.replay(m)
        assert set(out) == set(UIDS)
        st = surv.prefix_stats
        hit = st["matched_tokens"] - st0["matched_tokens"]
        ran = st["prefill_tokens"] - st0["prefill_tokens"]
        # the 10-token preamble (minus CoW tails) never re-prefills
        assert hit / (hit + ran) > 0.4
        toks = {u: list(s["generated"]) + [int(out[u])]
                for u, s in ((int(s["uid"]), s) for s in m["sequences"])}
        short = sorted(toks)
        outs = surv.decode_pipelined(
            short, [toks[u][-1] for u in short],
            [N_TOK - len(toks[u]) for u in short])
        for u in short:
            toks[u].extend(outs[u])
        assert toks == oracle

    def test_fused_decode_loop_replay_parity(self, oracle):
        # the fused n-token decode loop (decode_batch) commits its whole
        # burst in one readback; its replay bookkeeping (fed first token
        # + consumed outputs into gen_log, journal batched) must drain
        # and replay exactly like the per-step paths
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(prefix=False, decode_loop_steps=4))
        r = eng.put(list(UIDS), [list(p) for p in PROMPTS], _greedy=True)
        outs = eng.decode_batch(list(UIDS), [int(r[u]) for u in UIDS], 4)
        m = eng.drain()
        _assert_released(eng, m)
        for s in m["sequences"]:
            u = s["uid"]
            assert s["generated"] == [int(r[u])] + outs[u]
        _, toks = _replay_and_finish(m, _cfg(prefix=False))
        assert toks == oracle

    @pytest.mark.slow
    def test_drain_manifest_records_ledger(self, tmp_path):
        from deepspeed_tpu.resilience.ledger import RestartLedger
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg())
        _serve(eng, n=2)
        led = RestartLedger(str(tmp_path / "ledger.json"))
        m = eng.drain(str(tmp_path / "m.json"), ledger=led)
        ev = [e for e in led.events if e["event"] == "serve_drain"]
        assert len(ev) == 1
        assert ev[0]["sequences"] == len(m["sequences"]) == 3
        assert ev[0]["fully_recovered"] is True


class TestKillPointModel:
    """Randomized kill-point model: an injected fault (in-process
    ``raise`` mode — the drill covers hard ``os._exit``) at every serve
    pipeline stage, then drain + fresh-engine replay. Parity, no leaked
    blocks or refcounts, allocator full-capacity recovery."""

    def _kill_and_replay(self, oracle, site, skip, depth, prefix):
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg(prefix, depth))
        set_fault_injector(FaultInjector(site=site, mode="raise",
                                         skip=skip))
        fired = False
        try:
            try:
                _serve(eng)
            except InjectedFault:
                fired = True
        finally:
            set_fault_injector(None)
        m = eng.drain()
        _assert_released(eng, m)
        if not m["sequences"]:      # killed before the first admission
            assert fired
            return
        _, toks = _replay_and_finish(m, _cfg(prefix, depth))
        for u in toks:
            assert toks[u] == oracle[u], \
                f"site={site} skip={skip} depth={depth} prefix={prefix}"

    @pytest.mark.parametrize(
        "seed", [0, 1, pytest.param(2, marks=pytest.mark.slow)])
    def test_random_kill_replay_parity(self, oracle, seed):
        rng = np.random.default_rng(seed)
        site = SERVE_FAULT_SITES[rng.integers(0, len(SERVE_FAULT_SITES))]
        skip = int(rng.integers(0, 6))
        self._kill_and_replay(oracle, site, skip, depth=2, prefix=True)

    @pytest.mark.slow
    @pytest.mark.parametrize("depth", [0, 2, 3])
    @pytest.mark.parametrize("prefix", [True, False])
    def test_kill_grid(self, oracle, depth, prefix):
        # every serve site x this (depth, prefix) cell, x3 seeds for the
        # fire-point; during_cow_copy needs the cache on to ever fire
        # (a no-fire run degenerates to the plain drain test — fine)
        for seed in range(3):
            rng = np.random.default_rng(100 * depth + seed + int(prefix))
            for site in SERVE_FAULT_SITES:
                self._kill_and_replay(oracle, site, int(rng.integers(0, 6)),
                                      depth, prefix)


class TestAbort:
    """engine.abort(uid): safe any-time cancellation — frees deferred
    past in-flight steps, prefix refcounts released exactly."""

    def test_abort_unknown_uid(self):
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg())
        assert eng.abort(123) is False

    @pytest.mark.slow
    def test_abort_idle_releases_immediately(self, oracle):
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg(prefix=False))
        r = eng.put(list(UIDS), [list(p) for p in PROMPTS], _greedy=True)
        assert eng.abort(1) is True
        assert 1 not in eng.state.sequences
        live_blocks = sum(len(s.kv_blocks)
                          for s in eng.state.sequences.values())
        assert eng.free_blocks == eng.config.num_blocks - live_blocks
        # the survivors decode on, token-identical
        outs = eng.decode_pipelined([0, 2], [int(r[0]), int(r[2])],
                                    N_TOK - 1)
        for u in (0, 2):
            assert [int(r[u])] + outs[u] == oracle[u]

    def test_abort_mid_pipeline_defers_frees(self, oracle):
        # abort fired from inside a commit (the deadline/shed call site)
        # while later steps are still in flight: the victim's slots die,
        # its flush waits for the last in-flight step's commit, and the
        # allocator's exact double-free detection proves the deferral
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg(prefix=False, depth=2))
        r = eng.put(list(UIDS), [list(p) for p in PROMPTS], _greedy=True)
        orig, state = eng._pre_commit, {"n": 0}

        def hook(fl):
            orig(fl)
            state["n"] += 1
            if state["n"] == 3:            # mid-decode, ring non-empty
                assert eng.abort(1) is True
        eng._pre_commit = hook
        outs = eng.decode_pipelined(list(UIDS),
                                    [int(r[u]) for u in UIDS], N_TOK - 1)
        eng._pre_commit = orig
        assert 1 not in eng.state.sequences
        live_blocks = sum(len(s.kv_blocks)
                          for s in eng.state.sequences.values())
        assert eng.free_blocks == eng.config.num_blocks - live_blocks
        for u in (0, 2):                   # survivors unaffected
            assert [int(r[u])] + outs[u] == oracle[u]
        # the aborted stream is a prefix of its oracle (nothing invented)
        got = [int(r[1])] + outs[1]
        assert got == oracle[1][:len(got)]

    def test_abort_racing_eos_rollback_no_double_free(self):
        # a late EOS marks a sequence's later in-flight slots dead and
        # queues a deferred rollback; an abort() arriving before that
        # rollback's carrier step commits must not flush the blocks the
        # rollback will then trim again (allocator double-free) — the
        # review-found race behind deadline-abort + EOS interleavings
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg(
            prefix=False, depth=3, block_size=1, num_blocks=64,
            max_blocks_per_seq=32, attention_impl="dense"))
        prompt = list(np.random.default_rng(9).integers(1, 96, 10))
        f = eng.put([0], [prompt], _greedy=True)
        chain = eng.decode_pipelined([0], [int(f[0])], 8)[0]
        eng.flush(0)
        eos = chain[2]                     # EOS fires mid-ring at depth 3
        f = eng.put([1], [prompt], _greedy=True)
        orig, state = eng._pre_commit, {"done": False}

        def hook(fl):
            orig(fl)
            if fl.rollbacks and not state["done"]:
                state["done"] = True       # rollback carrier committing:
                eng.abort(1)               # the abort races the trim
        eng._pre_commit = hook
        out = eng.decode_pipelined([1], [int(f[1])], 8, eos_token_id=eos)
        eng._pre_commit = orig
        assert state["done"], "EOS rollback never queued — dead scenario"
        assert out[1] == chain[:3]         # stream ends at eos, as sync
        assert 1 not in eng.state.sequences
        assert eng.free_blocks == eng.config.num_blocks

    def test_abort_shared_prefix_refcounts_exact(self):
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg())
        _serve(eng, n=3)
        assert eng.abort(1) is True
        eng._prefix.check_invariants()
        for u in (0, 2):
            eng.flush(u)
        eng._prefix.check_invariants()
        assert eng._prefix.evictable_blocks == eng._prefix.cached_blocks
        assert eng.free_blocks == eng.config.num_blocks


class TestJournalReplay:
    """The write-ahead journal: a hard crash (no drain ran) still
    recovers every COMMITTED token from the JSONL log."""

    def test_journal_crash_replay_parity(self, oracle, tmp_path):
        jpath = str(tmp_path / "serve.jsonl")
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(serve_journal=jpath))
        partial = _serve(eng, n=4)
        # hard crash: NO drain — the journal alone carries the state
        del eng
        m = manifest_from_journal(jpath)
        assert m["source"] == "journal"
        got = {int(s["uid"]): s["generated"] for s in m["sequences"]}
        assert got == partial
        _, toks = _replay_and_finish(m, _cfg())
        assert toks == oracle

    def test_journal_finish_drops_sequence(self, tmp_path):
        jpath = str(tmp_path / "serve.jsonl")
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(serve_journal=jpath))
        _serve(eng, n=2)
        eng.flush(1)                       # journals the finish
        m = manifest_from_journal(jpath)
        assert sorted(int(s["uid"]) for s in m["sequences"]) == [0, 2]

    def test_journal_torn_tail_tolerated(self, tmp_path):
        jpath = str(tmp_path / "serve.jsonl")
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(serve_journal=jpath))
        partial = _serve(eng, n=3)
        with open(jpath, "a") as f:
            f.write('{"e": "tokens", "t": {"0": [7')   # killed mid-write
        m = manifest_from_journal(jpath)
        got = {int(s["uid"]): s["generated"] for s in m["sequences"]}
        assert got == partial              # committed prefix intact

    @pytest.mark.slow
    def test_drain_leaves_journal_intact_as_fallback(self, oracle,
                                                     tmp_path):
        # the drain flush must NOT append 'finish' records for the
        # sequences the manifest still owes to a survivor: if the drain
        # itself dies before write_manifest lands, the journal is the
        # only recovery channel left (review-found torn-drain hole)
        jpath = str(tmp_path / "serve.jsonl")
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg(serve_journal=jpath))
        partial = _serve(eng, n=4)
        m = eng.drain()
        assert len(m["sequences"]) == 3
        m2 = manifest_from_journal(jpath)
        got = {int(s["uid"]): s["generated"] for s in m2["sequences"]}
        assert got == partial              # all three still recoverable
        _, toks = _replay_and_finish(m2, _cfg())
        assert toks == oracle

    def test_load_replay_state_prefers_manifest(self, tmp_path):
        mpath, jpath = str(tmp_path / "m.json"), str(tmp_path / "j.jsonl")
        write_manifest({"version": 1, "source": "drain",
                        "sequences": []}, mpath)
        with open(jpath, "w") as f:
            f.write(json.dumps({"e": "admit", "uid": 3,
                                "prompt": [1, 2]}) + "\n")
        assert load_replay_state(mpath, jpath)["source"] == "drain"
        assert load_replay_state(None, jpath)["source"] == "journal"
        assert load_replay_state(str(tmp_path / "nope.json"), None) is None


class TestDeadlinesShedRetry:
    """Request deadlines, graceful load shedding, bounded retry — the
    crash-free failure paths of the serve loop."""

    def test_deadline_expiry_aborts_with_rejection(self, oracle):
        mcfg, params = _gpt2()
        # a roomy deadline so admission stamping never fires on its own
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(prefix=False, request_deadline_s=60))
        r = eng.put(list(UIDS), [list(p) for p in PROMPTS], _greedy=True)
        for u in UIDS:
            assert eng.state.sequences[u].deadline_at is not None
        eng.state.sequences[1].deadline_at = time.monotonic() - 1
        outs = eng.decode_pipelined(list(UIDS),
                                    [int(r[u]) for u in UIDS], N_TOK - 1)
        rej = eng.rejections[1]
        assert rej["reason"] == "deadline_exceeded"
        assert rej["deadline_s"] == 60
        assert 1 not in eng.state.sequences
        for u in (0, 2):                   # on-time requests unaffected
            assert [int(r[u])] + outs[u] == oracle[u]
        # a request that COMPLETED its budget on time owes nothing: an
        # expired deadline on its idle descriptor must not reap it
        # while other traffic decodes (review finding — late-503 for an
        # already-answered request)
        eng.state.sequences[0].deadline_at = time.monotonic() - 1
        more = eng.decode_pipelined([2], [outs[2][-1]], 2)
        assert 0 not in eng.rejections
        assert 0 in eng.state.sequences
        assert len(more[2]) == 2

    def test_decode_outgrows_pool_sheds_gracefully(self):
        mcfg, params = _gpt2()
        # prompt (13) + first token fills the 4-block pool exactly; the
        # next decode token needs a 5th block -> starvation mid-flight
        eng = InferenceEngineV2(mcfg, params, _cfg(
            prefix=False, num_blocks=4, max_seqs=2))
        prompt = list(np.random.default_rng(3).integers(1, 96, 13))
        r = eng.put([0], [prompt], _greedy=True)
        outs = eng.decode_pipelined([0], [int(r[0])], 8)
        assert len(outs[0]) < 8            # shed before the budget
        assert eng.rejections[0]["reason"] == "kv_pool_exhausted"
        assert 0 not in eng.state.sequences
        assert eng.free_blocks == 4        # full-capacity recovery
        # and the engine keeps serving new traffic
        ok = eng.put([1], [[5, 6, 7]], _greedy=True)
        assert 1 in ok

    @pytest.mark.slow
    def test_decode_outgrows_pool_hard_mode_raises(self):
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg(
            prefix=False, num_blocks=4, max_seqs=2, serve_shed=False))
        prompt = list(np.random.default_rng(3).integers(1, 96, 13))
        r = eng.put([0], [prompt], _greedy=True)
        with pytest.raises(RuntimeError, match="starved"):
            eng.decode_pipelined([0], [int(r[0])], 8)

    def test_transient_dispatch_failure_retries(self, oracle):
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg(
            prefix=False, serve_retry_backoff_s=0.0))
        set_fault_injector(FaultInjector(site="pre_dispatch",
                                         mode="ioerror", times=2))
        try:
            toks = _serve(eng)
        finally:
            set_fault_injector(None)
        assert eng.pipeline_stats["retries"] == 2
        assert toks == oracle              # retries are invisible

    @pytest.mark.slow
    def test_persistent_dispatch_failure_surfaces_then_drains(self, oracle):
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params, _cfg(
            prefix=False, serve_retry_backoff_s=0.0, serve_step_retries=2))
        set_fault_injector(FaultInjector(site="pre_dispatch",
                                         mode="ioerror", times=1000))
        try:
            with pytest.raises(ServeStepError):
                _serve(eng)
        finally:
            set_fault_injector(None)
        # the drained state is still consistent and replayable
        m = eng.drain()
        _assert_released(eng, m)
        if m["sequences"]:
            _, toks = _replay_and_finish(m, _cfg(prefix=False))
            for u in toks:
                assert toks[u] == oracle[u]


class TestServeDrainPrograms:
    """The drain/replay layer must add NOTHING to the device story:
    replay on a warm engine compiles no fresh programs, and the serve
    programs stay collective/callback-clean at tp1."""

    @pytest.mark.slow
    def test_replay_warm_zero_fresh_compiles_and_clean_programs(self):
        from deepspeed_tpu.analysis import RecompileTripwire
        from deepspeed_tpu.analysis.program_audit import (
            CollectiveBudget, assert_budget, audit_serve_programs)
        mcfg, params = _gpt2()
        dead = InferenceEngineV2(mcfg, params, _cfg())
        _serve(dead, n=4)
        m = dead.drain()
        surv = InferenceEngineV2(mcfg, params, _cfg())
        _serve(surv, uids=(7,), prompts=(_SHARED + [3, 1, 4, 1, 5],),
               n=N_TOK)                    # warm every program
        surv.flush(7)
        tw = RecompileTripwire()
        with tw:
            out = surv.replay(m)
            short = sorted(int(s["uid"]) for s in m["sequences"])
            surv.decode_pipelined(short, [int(out[u]) for u in short], 3)
        if tw.available:
            assert tw.fresh_compiles == 0
        # drain-path device programs: zero collectives, zero callbacks
        reports = audit_serve_programs(surv)
        clean = CollectiveBudget(name="tp1 serve after drain/replay")
        for name, rep in reports.items():
            assert_budget(rep, clean)
            assert rep.host_callbacks == 0, name

    @pytest.mark.slow
    def test_llama_drain_replay_parity(self):
        from deepspeed_tpu.models.llama import Llama, LlamaConfig
        mcfg = LlamaConfig.tiny(dtype=jnp.float32, attention_impl="xla")
        params = Llama(mcfg).init(jax.random.PRNGKey(0),
                                  jnp.zeros((1, 8), jnp.int32))["params"]
        prompts = tuple(_SHARED + t for t in ([7, 1, 3], [2, 9, 4]))
        base = _cfg()
        eng0 = InferenceEngineV2(mcfg, params, base)
        want = _serve(eng0, uids=(0, 1), prompts=prompts, n=6)
        eng = InferenceEngineV2(mcfg, params, base)
        _serve(eng, uids=(0, 1), prompts=prompts, n=3)
        m = eng.drain()
        _assert_released(eng, m)
        eng2, toks = _replay_and_finish(m, base, n=6,
                                        model=(mcfg, params))
        assert toks == want

    @pytest.mark.slow
    def test_tp2_drain_replay_parity(self):
        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices")
        mcfg, params = _gpt2()
        base = _cfg(tp_size=2, max_seqs=2)
        prompts = (PROMPTS[0], PROMPTS[1])
        eng0 = InferenceEngineV2(mcfg, params, base)
        want = _serve(eng0, uids=(0, 1), prompts=prompts, n=6)
        eng = InferenceEngineV2(mcfg, params, base)
        _serve(eng, uids=(0, 1), prompts=prompts, n=3)
        m = eng.drain()
        _assert_released(eng, m)
        _, toks = _replay_and_finish(m, base, n=6)
        assert toks == want
