"""Sequence-parallel serving tests (ISSUE 18): context-parallel prefill +
sequence-sharded paged attention (inference/v2/seq_parallel.py).

The contract under test: ``seq_size=2`` on the 8-device CPU mesh yields
TOKEN-IDENTICAL streams to the ``seq_size=1`` oracle across greedy,
sampled, speculative, prefix-cache and int8-pool serving; per-chip KV
pool bytes halve (the long-context capacity lever); the seq axis's comm
is exactly budgeted (ring hops = seq-1 ppermutes + 1 fresh-KV all-gather
per layer in prefill, 1 stat-combine all-gather per layer per fused
decode step, 1 owner psum per step program); drain/handoff manifests
cross seq geometries; the warm path stays compile-free; and
``DSTPU_SEQ_PARALLEL=0`` restores the exact pre-seq programs (zero
collectives under the auditor).

Tier-1 wall discipline: params init and every engine build compile real
XLA programs on the 1-core harness, so the default-geometry oracle
(seq=1) and seq=2 engines are MODULE-scoped and shared across the
parity / budget / warm tests (``generate`` flushes its sequences, and
the program auditor only traces, so sharing is state-safe); only tests
that mutate engine lifecycle (drain/handoff) or need a different config
(spec, prefix, int8, chunk=7) build their own.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.analysis import (CollectiveBudget, RecompileTripwire,
                                    assert_budget, audit_serve_programs,
                                    budget_args)
from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                        RaggedInferenceConfig,
                                        SamplingParams)
from deepspeed_tpu.inference.v2.blocked_allocator import (BlockedAllocator,
                                                          OutOfBlocksError)
from deepspeed_tpu.inference.v2.seq_parallel import slot_rows
from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config

L = 2          # layers of the tiny model below
SEQ_AXIS = "seq"


def _setup(num_heads=4, hidden=64, vocab=96, **cfg_kw):
    mcfg = GPT2Config(vocab_size=vocab, max_seq_len=128, num_layers=L,
                      num_heads=num_heads, hidden_size=hidden,
                      dtype=jnp.float32)
    params = GPT2(mcfg).init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 8), jnp.int32))["params"]
    base = dict(max_seqs=4, chunk_size=8, block_size=4, num_blocks=64,
                max_blocks_per_seq=16, dtype="float32",
                attention_impl="dense", decode_loop_steps=4)
    base.update(cfg_kw)
    return mcfg, params, base


def _prompts(seed=21, n=3, lens=(9, 17, 26)):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 96, lens[i % len(lens)]).tolist()
            for i in range(n)]


@pytest.fixture(scope="module")
def base_pair():
    """(mcfg, params, base-config) shared module-wide — PRNGKey(0) makes
    params deterministic, so inline engines built from this triple stay
    stream-identical to the shared oracle below."""
    return _setup()


@pytest.fixture(scope="module")
def oracle(base_pair):
    """The seq=1 oracle engine (default geometry), built once."""
    mcfg, params, base = base_pair
    return InferenceEngineV2(mcfg, params, RaggedInferenceConfig(**base))


@pytest.fixture(scope="module")
def seq2(base_pair):
    """The seq=2 engine (default geometry), built once."""
    mcfg, params, base = base_pair
    return InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
        **base, seq_size=2))


@pytest.fixture(scope="module")
def seq2_reports(seq2):
    return audit_serve_programs(seq2)


# ------------------------------------------------------------------ #
# host-side layout: allocator homes + pool row math
# ------------------------------------------------------------------ #


class TestSeqLayout:

    def test_allocator_single_home_is_historical(self):
        a = BlockedAllocator(8)
        assert a.allocate(3) == [0, 1, 2]
        a.free([1])
        assert a.allocate(1) == [1]

    def test_allocator_homes_round_robin(self):
        a = BlockedAllocator(8, num_homes=2)
        # a chain's ordinals land on homes 0,1,0,1 and stay balanced
        got = a.allocate(4, homes=[0, 1, 0, 1])
        assert [b % 2 for b in got] == [0, 1, 0, 1]
        assert a.free_in_home(0) == a.free_in_home(1) == 2
        # a dry home fails even while the TOTAL could cover the ask
        with pytest.raises(OutOfBlocksError):
            a.allocate(3, homes=[0, 0, 0])
        assert a.shortfall([0, 0, 0]) == [1, 0]
        a.free(got)
        assert a.free_blocks == 8

    def test_slot_rows_seq1_is_classic_layout(self):
        rows = slot_rows([0, 3, 5], block_size=4, num_blocks=64, seq=1)
        want = np.concatenate([np.arange(b * 4, b * 4 + 4)
                               for b in (0, 3, 5)])
        assert (rows == want).all()

    def test_slot_rows_seq2_round_robin_shards(self):
        # block b lives in shard b % 2 at local index b // 2; each
        # shard carries (num_blocks//2 + 1) * bs rows (own trash last)
        shard_rows = (64 // 2 + 1) * 4
        rows = slot_rows([0, 1, 2], block_size=4, num_blocks=64, seq=2)
        assert (rows[:4] == np.arange(4)).all()                 # b0 -> s0
        assert (rows[4:8] == shard_rows + np.arange(4)).all()   # b1 -> s1
        assert (rows[8:12] == 4 + np.arange(4)).all()           # b2 -> s0

    def test_config_rejects_bad_seq_geometry(self):
        with pytest.raises(ValueError):
            RaggedInferenceConfig(seq_size=2, num_blocks=63)
        with pytest.raises(ValueError):
            RaggedInferenceConfig(seq_size=2, tp_size=2)
        with pytest.raises(ValueError):
            RaggedInferenceConfig(seq_size=2, max_blocks_per_seq=15)

    def test_effective_chunk_rounds_up_to_seq(self):
        # ISSUE 18 satellite bugfix: effective_chunk must divide evenly
        # across the seq axis — the last sub-chunk pads, it never emits
        # a zero-token shard
        cfg = RaggedInferenceConfig(chunk_size=7, seq_size=2,
                                    max_blocks_per_seq=16)
        assert cfg.effective_chunk == 8
        assert cfg.effective_chunk % 2 == 0
        assert cfg.effective_chunk // 2 >= 1
        # seq=1 keeps the historical chunk exactly
        assert RaggedInferenceConfig(chunk_size=7).effective_chunk == 7


# ------------------------------------------------------------------ #
# token parity seq in {1, 2} x serving modes
# ------------------------------------------------------------------ #


class TestSeqParity:
    """Greedy/sampled/spec/prefix/int8 streams must be identical across
    seq sizes — the seq axis is a layout change, not a model change."""

    def test_seq2_greedy_token_identical_and_kv_flat(self, oracle, seq2):
        prompts = _prompts()
        ref = oracle.generate(prompts, max_new_tokens=6)
        assert seq2.generate(prompts, max_new_tokens=6) == ref
        rep = seq2.state.kv_memory_report()
        assert rep["seq_size"] == 2
        # per-chip pool bytes halve: the long-context capacity lever
        assert rep["kv_pool_bytes_per_chip"] * 2 == \
            rep["kv_pool_bytes_total"]

    def test_seq2_sampled_token_identical(self, oracle, seq2):
        prompts = _prompts(seed=5)
        sp = SamplingParams(temperature=0.8, top_k=20, seed=13)
        ref = oracle.generate(prompts, max_new_tokens=6, sampling=sp)
        got = seq2.generate(prompts, max_new_tokens=6, sampling=sp)
        assert got == ref

    def test_seq2_spec_ngram_token_identical(self, base_pair):
        # speculation is lossless, so it composes: seq=2 spec streams
        # == seq=1 spec streams (periodic prompts feed the n-gram
        # proposer actual acceptances)
        mcfg, params, base = base_pair
        pat = np.random.default_rng(3).integers(1, 96, 6).tolist()
        prompts = [(pat * 4)[:14], (pat * 4)[:19]]
        kw = dict(spec_decode="ngram", spec_k=4)
        ref = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
            **base, **kw)).generate(prompts, max_new_tokens=8)
        got = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
            **base, seq_size=2, **kw)).generate(prompts, max_new_tokens=8)
        assert got == ref

    def test_seq2_prefix_cache_token_identical(self, base_pair):
        # shared preambles: the second wave hits the cache (CoW +
        # home-aligned prefix chains) and still matches the oracle
        mcfg, params, base = base_pair
        rng = np.random.default_rng(11)
        pre = rng.integers(1, 96, 8).tolist()
        prompts = [pre + rng.integers(1, 96, 7).tolist()
                   for _ in range(3)]

        def run(seq):
            eng = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
                **base, prefix_cache=True, seq_size=seq))
            first = eng.generate(prompts[:2], max_new_tokens=5)
            second = eng.generate(prompts, max_new_tokens=5)
            return first, second, eng.prefix_stats["matched_tokens"]

        ref_a, ref_b, ref_hits = run(1)
        got_a, got_b, got_hits = run(2)
        assert (got_a, got_b) == (ref_a, ref_b)
        assert got_hits == ref_hits and got_hits > 0

    def test_seq2_int8_pool_token_identical(self, base_pair, int8_seq2):
        # every chip quantizes the gathered fresh chunk identically, so
        # int8 pool bytes — and the streams — match the seq=1 engine
        mcfg, params, base = base_pair
        prompts = _prompts(seed=7)
        ref = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
            **base, kv_cache_dtype="int8")).generate(
                prompts, max_new_tokens=6)
        got = int8_seq2.generate(prompts, max_new_tokens=6)
        assert got == ref

    @pytest.mark.full
    def test_seq4_greedy_token_identical(self, base_pair, oracle):
        mcfg, params, base = base_pair
        prompts = _prompts(seed=9)
        ref = oracle.generate(prompts, max_new_tokens=6)
        got = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
            **base, seq_size=4)).generate(prompts, max_new_tokens=6)
        assert got == ref

    def test_chunk_not_divisible_by_seq_regression(self, base_pair):
        # ISSUE 18 satellite bugfix regression: chunk_size=7 with seq=2
        # (effective_chunk rounds to 8) — prefill chunks, replay tails
        # and C=1 decode steps all pad instead of emitting a zero-token
        # shard, and streams stay identical to the seq=1 oracle AT THE
        # SAME effective chunk
        mcfg, params, base = base_pair
        cfg7 = dict(base, chunk_size=7)
        prompts = _prompts(seed=13)
        ref = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
            **cfg7)).generate(prompts, max_new_tokens=6)
        eng = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
            **cfg7, seq_size=2))
        assert eng.config.effective_chunk == 8
        assert eng.generate(prompts, max_new_tokens=6) == ref

    def test_killswitch_restores_single_chip_engine(self, base_pair,
                                                    oracle, monkeypatch):
        # DSTPU_SEQ_PARALLEL=0 must yield the exact pre-seq engine:
        # seq_size resolves to 1, programs carry ZERO collectives (the
        # auditor sees no diff vs the single-chip baseline), tokens match
        mcfg, params, base = base_pair
        monkeypatch.setenv("DSTPU_SEQ_PARALLEL", "0")
        eng = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
            **base, seq_size=2))
        assert eng.config.seq_size == 1
        prompts = _prompts(seed=17)
        monkeypatch.delenv("DSTPU_SEQ_PARALLEL")
        ref = oracle.generate(prompts, max_new_tokens=5)
        assert eng.generate(prompts, max_new_tokens=5) == ref
        for name, rep in audit_serve_programs(eng).items():
            assert rep.total_collectives == 0, (name, rep.summary())


# ------------------------------------------------------------------ #
# drain / handoff across seq geometries
# ------------------------------------------------------------------ #


class TestSeqDrainHandoff:

    def test_drain_replay_parity_across_geometries(self, base_pair,
                                                   oracle):
        # drain a seq=2 engine mid-stream, replay the manifest on a
        # seq=1 engine (and vice versa): continuations token-identical
        # to the uninterrupted oracle — the manifest records the shard
        # map but replay is geometry-free
        mcfg, params, base = base_pair
        prompts = {100: _prompts(seed=19)[0], 101: _prompts(seed=19)[1]}
        want = oracle.generate(list(prompts.values()), max_new_tokens=8)
        for src_seq, dst_seq in ((2, 1), (1, 2)):
            src = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
                **base, seq_size=src_seq))
            uids = list(prompts)
            first = src.put(uids, list(prompts.values()), _greedy=True)
            got = {u: [first[u]] for u in uids}
            step1 = src.decode_pipelined(uids, [first[u] for u in uids], 3)
            for u in uids:
                got[u].extend(step1[u])
            m = src.drain()
            assert m["config"]["seq_size"] == src_seq
            dst = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
                **base, seq_size=dst_seq))
            out = dst.replay(m)        # replay itself emits a token
            for u in uids:
                got[u].append(int(out[u]))
            more = dst.decode_pipelined(uids, [got[u][-1] for u in uids],
                                        3)
            for u in uids:
                got[u].extend(more[u])
            for i, u in enumerate(uids):
                assert got[u] == want[i], (src_seq, dst_seq, u)

    def test_handoff_manifest_carries_shard_map(self, base_pair, oracle):
        # disagg handoff out of a seq=2 replica into a seq=1 one: the
        # manifest carries seq_size, the destination continues the
        # stream token-identically (block-ordered payloads are
        # geometry-free)
        mcfg, params, base = base_pair
        prompts = {7: _prompts(seed=23)[0]}
        want = oracle.generate(list(prompts.values()),
                               max_new_tokens=7)[0]
        src = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
            **base, seq_size=2))
        first = src.put([7], list(prompts.values()), _greedy=True)
        got = [first[7]]
        got.extend(src.decode_pipelined([7], [first[7]], 2)[7])
        m = src.handoff_out([7])
        assert m["seq_size"] == 2
        assert len(m["sequences"]) == 1
        dst = InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
            **base))
        res = dst.handoff_in(m)
        assert res["accepted"] == [7] and not res["spilled"]
        got.extend(dst.decode_pipelined([7], [got[-1]], 4)[7])
        assert got == want


# ------------------------------------------------------------------ #
# audited hop budgets + warm-path compile hygiene
# ------------------------------------------------------------------ #


@pytest.fixture(scope="module")
def int8_seq2(base_pair):
    """int8-pool seq=2 engine, shared by the int8 parity and scale-ride
    budget tests."""
    mcfg, params, base = base_pair
    return InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
        **base, kv_cache_dtype="int8", seq_size=2))


class TestSeqHopBudget:
    """ISSUE 18 acceptance: the seq axis's comm is exactly what the
    design says — nothing extra rides along."""

    def test_step_ring_budget(self, seq2_reports):
        # per layer: 1 fresh-KV all-gather + (seq-1)=1 ring ppermute;
        # per program: 1 owner-logits psum (GPT-2's tied unembed adds
        # no logits gather) — the spec lives in the shared registry
        # (analysis/budgets.py "seq-step"), the same one bench.py's
        # serve_longctx asserts and dslint DSL008 cross-checks
        budget = CollectiveBudget(**budget_args(
            "seq-step", num_layers=L, seq=2, label="seq2-step"))
        for name in ("step", "step_greedy", "step_greedy_fb"):
            assert_budget(seq2_reports[name], budget)

    def test_decode_loop_stat_combine_budget(self, seq2_reports):
        # the fused loop: ONE packed stat-combine all-gather per layer
        # per step, zero per-program collectives (every chip computes
        # identical merged logits), scan trip-weighted over 4 steps
        assert_budget(seq2_reports["decode_loop"], CollectiveBudget(
            **budget_args("seq-decode-loop", num_layers=L, seq=2,
                          steps=4, label="seq2-decode-loop")))

    def test_flush_ring_chip_local(self, seq2_reports):
        # the ownership-masked flush scatter is chip-local: zero comm
        assert_budget(seq2_reports["flush_ring"], CollectiveBudget(
            **budget_args("seq-flush", num_layers=L, seq=2,
                          label="seq2-flush")))

    def test_int8_scale_planes_ride_the_ring(self, int8_seq2):
        # over an int8 pool the ring doubles: per hop one int8 data
        # ppermute + one f32 scale-plane ppermute (the PR 6 quantized-
        # collective shape), while the fresh-KV exchange stays ONE
        # compute-dtype all-gather — expectations derive from the
        # registry's dtype-pinned "seq-step-int8" entry
        rep = audit_serve_programs(int8_seq2, programs=("step",))["step"]
        exp = CollectiveBudget(**budget_args(
            "seq-step-int8", num_layers=L, seq=2)).expected()
        assert rep.count(kind="ppermute", dtype="int8") \
            == exp["ppermute@int8"]
        assert rep.count(kind="ppermute", dtype="float32") \
            == exp["ppermute@float32"]
        assert rep.count(kind="all_gather", dtype="float32") \
            == exp["all_gather@float32"]

    def test_seq4_ring_hops_scale(self, base_pair):
        # seq=4: (seq-1)=3 ring hops per layer, still 1 all-gather —
        # the SAME registry entry as seq=2, resolved at a wider shard
        mcfg, params, base = base_pair
        rep = audit_serve_programs(
            InferenceEngineV2(mcfg, params, RaggedInferenceConfig(
                **base, seq_size=4)), programs=("step",))["step"]
        assert_budget(rep, CollectiveBudget(**budget_args(
            "seq-step", num_layers=L, seq=4, label="seq4-step")))


class TestSeqWarmPath:

    def test_warm_pipeline_zero_fresh_compiles(self, seq2):
        # the shared seq=2 engine has served the parity generates by
        # now, so its programs are compiled — one put+pipelined-decode
        # primes any remaining shape, then the measured window must be
        # compile-free (a miss here is a shape/dtype/static-arg leak in
        # the seq slice wrapper)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, 96, 6).tolist() for _ in range(2)]
        uids = [70, 71]
        tw = RecompileTripwire()
        if not tw.available:
            pytest.skip("jax monitoring API unavailable")
        first = seq2.put(uids, prompts, _greedy=True)
        seq2.decode_pipelined(uids, [first[u] for u in uids], 4)
        with RecompileTripwire() as warm:
            seq2.decode_pipelined(
                uids, [int(rng.integers(1, 96)) for _ in uids], 4)
        assert warm.fresh_compiles == 0, (
            f"{warm.fresh_compiles} jit cache misses on a warm seq=2 "
            f"pipeline run")
        for u in uids:
            seq2.flush(u)
