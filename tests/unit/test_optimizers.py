"""Optimizer factory tests — analogue of reference tests/unit/ops/adam etc."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.optimizers import build_optimizer


def _step(tx, params, grads):
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    return jax.tree_util.tree_map(lambda p, u: p + u, params, updates)


PARAMS = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
GRADS = {"w": jnp.full((4, 4), 0.5), "b": jnp.full((4,), 0.1)}


@pytest.mark.parametrize("name", ["Adam", "AdamW", "FusedAdam", "Lamb", "Lion",
                                  "Adagrad", "SGD", "OneBitAdam"])
def test_all_types_step(name):
    tx = build_optimizer(name, {"lr": 1e-2, "weight_decay": 0.01})
    new = _step(tx, PARAMS, GRADS)
    assert not np.allclose(np.asarray(new["w"]), np.asarray(PARAMS["w"]))


def test_fusedadam_weight_decay_applied():
    """FusedAdam defaults to adam_w_mode=True: weight decay must shrink a
    parameter that has zero gradient."""
    tx = build_optimizer("FusedAdam", {"lr": 1e-1, "weight_decay": 0.5})
    params = {"w": jnp.ones((4,))}
    grads = {"w": jnp.zeros((4,))}
    new = _step(tx, params, grads)
    assert float(new["w"][0]) < 1.0, "decoupled weight decay was dropped"


def test_adam_l2_mode():
    """adam_w_mode=False: classic L2 — decay folds into the gradient, so a
    zero-grad param still moves (through the Adam moments)."""
    tx = build_optimizer("Adam", {"lr": 1e-1, "weight_decay": 0.5,
                                  "adam_w_mode": False})
    params = {"w": jnp.ones((4,))}
    grads = {"w": jnp.zeros((4,))}
    new = _step(tx, params, grads)
    assert float(new["w"][0]) < 1.0


def test_unknown_raises():
    with pytest.raises(ValueError):
        build_optimizer("NotAnOptimizer", {})


def test_schedule_as_lr():
    sched = lambda step: 0.1 / (1.0 + step)
    tx = build_optimizer("SGD", {}, learning_rate=sched)
    new = _step(tx, PARAMS, GRADS)
    np.testing.assert_allclose(np.asarray(new["w"]),
                               np.asarray(PARAMS["w"]) - 0.1 * 0.5, rtol=1e-5)
