"""Optimizer factory tests — analogue of reference tests/unit/ops/adam etc."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.optimizers import build_optimizer


def _step(tx, params, grads):
    state = tx.init(params)
    updates, _ = tx.update(grads, state, params)
    return jax.tree_util.tree_map(lambda p, u: p + u, params, updates)


PARAMS = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
GRADS = {"w": jnp.full((4, 4), 0.5), "b": jnp.full((4,), 0.1)}


@pytest.mark.parametrize("name", ["Adam", "AdamW", "FusedAdam", "Lamb", "Lion",
                                  "Adagrad", "SGD", "OneBitAdam"])
def test_all_types_step(name):
    tx = build_optimizer(name, {"lr": 1e-2, "weight_decay": 0.01})
    new = _step(tx, PARAMS, GRADS)
    assert not np.allclose(np.asarray(new["w"]), np.asarray(PARAMS["w"]))


def test_fusedadam_weight_decay_applied():
    """FusedAdam defaults to adam_w_mode=True: weight decay must shrink a
    parameter that has zero gradient."""
    tx = build_optimizer("FusedAdam", {"lr": 1e-1, "weight_decay": 0.5})
    params = {"w": jnp.ones((4,))}
    grads = {"w": jnp.zeros((4,))}
    new = _step(tx, params, grads)
    assert float(new["w"][0]) < 1.0, "decoupled weight decay was dropped"


def test_adam_l2_mode():
    """adam_w_mode=False: classic L2 — decay folds into the gradient, so a
    zero-grad param still moves (through the Adam moments)."""
    tx = build_optimizer("Adam", {"lr": 1e-1, "weight_decay": 0.5,
                                  "adam_w_mode": False})
    params = {"w": jnp.ones((4,))}
    grads = {"w": jnp.zeros((4,))}
    new = _step(tx, params, grads)
    assert float(new["w"][0]) < 1.0


def test_unknown_raises():
    with pytest.raises(ValueError):
        build_optimizer("NotAnOptimizer", {})


def test_schedule_as_lr():
    sched = lambda step: 0.1 / (1.0 + step)
    tx = build_optimizer("SGD", {}, learning_rate=sched)
    new = _step(tx, PARAMS, GRADS)
    np.testing.assert_allclose(np.asarray(new["w"]),
                               np.asarray(PARAMS["w"]) - 0.1 * 0.5, rtol=1e-5)


class TestCompactAdamW:
    """bf16-stored-moment AdamW (ops/optimizers.adamw_compact) — the
    chip-residency optimizer behind the 1.3B single-chip bench phase."""

    def test_moment_dtypes_and_dispatch(self):
        import optax
        tx = build_optimizer("AdamW", {"lr": 1e-2, "weight_decay": 0.01,
                                       "moment_dtype": "bfloat16"})
        p = {"w": jnp.ones((8, 8), jnp.float32)}
        st = tx.init(p)
        assert jax.tree_util.tree_leaves(st.mu)[0].dtype == jnp.bfloat16
        assert jax.tree_util.tree_leaves(st.nu)[0].dtype == jnp.bfloat16

    def test_trajectory_tracks_fp32_adamw(self):
        import optax
        tx = build_optimizer("AdamW", {"lr": 1e-2, "weight_decay": 0.01,
                                       "moment_dtype": "bfloat16"})
        ref = optax.adamw(1e-2, weight_decay=0.01)
        key = jax.random.PRNGKey(0)
        p = pr = {"w": jax.random.normal(key, (16, 16))}
        st, str_ = tx.init(p), ref.init(pr)
        for i in range(25):
            g = {"w": jax.random.normal(jax.random.PRNGKey(i), (16, 16))}
            u, st = tx.update(g, st, p)
            p = optax.apply_updates(p, u)
            ur, str_ = ref.update(g, str_, pr)
            pr = optax.apply_updates(pr, ur)
        # bf16 moments: trajectories agree to ~bf16 relative precision
        d = float(jnp.max(jnp.abs(p["w"] - pr["w"])))
        s = float(jnp.max(jnp.abs(pr["w"])))
        assert d / s < 0.05, (d, s)

    def test_sqrt_nu_storage_preserves_small_variance(self):
        # nu stored as sqrt(nu) in bf16: a grad of 1e-3 gives nu ~ 1e-8,
        # far below bf16's tiny-value resolution if stored directly, but
        # sqrt(nu) ~ 1e-4 survives — the update must be nonzero and sane
        tx = build_optimizer("AdamW", {"lr": 1e-2, "weight_decay": 0.0,
                                       "moment_dtype": "bfloat16"})
        p = {"w": jnp.ones((4,), jnp.float32)}
        st = tx.init(p)
        g = {"w": jnp.full((4,), 1e-3)}
        for _ in range(10):
            u, st = tx.update(g, st, p)
        # adam normalizes: update magnitude ~ lr regardless of grad scale
        assert 1e-3 < abs(float(u["w"][0])) < 2e-2
