"""Model zoo tests — llama family (RoPE/GQA/SwiGLU/sliding window), mixtral
MoE, BERT MLM, HF config mapping, and ragged-runner parity for llama/mixtral.
Mirrors the reference's per-arch container tests
(``tests/unit/inference/test_inference.py`` model zoo sweep) at tiny scale."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.inference.v2 import InferenceEngineV2, RaggedInferenceConfig
from deepspeed_tpu.models import bert, llama, mixtral
from deepspeed_tpu.models.registry import config_from_hf, get_arch


class TestLlama:
    def test_forward_shapes_gqa(self):
        cfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
        model, init_fn, _ = llama.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0))
        logits = model.apply({"params": params},
                             jnp.zeros((2, 16), jnp.int32))
        assert logits.shape == (2, 16, cfg.vocab_size)
        # GQA: k_proj is narrower than q_proj
        l0 = params["layer_0"]["attn"]
        assert l0["k_proj"]["kernel"].shape[1] == \
            cfg.num_kv_heads * cfg.head_dim
        assert l0["q_proj"]["kernel"].shape[1] == \
            cfg.num_heads * cfg.head_dim

    def test_rope_properties(self):
        """RoPE is a rotation (norm-preserving) and relative (scores depend
        only on position deltas)."""
        rng = jax.random.PRNGKey(0)
        q = jax.random.normal(rng, (1, 6, 2, 16))
        k = jax.random.normal(jax.random.fold_in(rng, 1), (1, 6, 2, 16))
        pos = jnp.arange(6)[None, :]
        qr = llama.apply_rope(q, pos, 10000.0)
        kr = llama.apply_rope(k, pos, 10000.0)
        np.testing.assert_allclose(np.linalg.norm(np.asarray(qr), axis=-1),
                                   np.linalg.norm(np.asarray(q), axis=-1),
                                   atol=1e-5)
        # shifting both positions by s leaves q_i . k_j unchanged
        qs = llama.apply_rope(q, pos + 11, 10000.0)
        ks = llama.apply_rope(k, pos + 11, 10000.0)
        s1 = jnp.einsum("bthd,bshd->bhts", qr, kr)
        s2 = jnp.einsum("bthd,bshd->bhts", qs, ks)
        np.testing.assert_allclose(s1, s2, atol=1e-4)
        # absolute rotation is position-dependent
        assert not np.allclose(qr[0, 0], qr[0, 5], atol=1e-3)

    def test_sliding_window_masks_distant_tokens(self):
        cfg = llama.LlamaConfig.tiny(dtype=jnp.float32, sliding_window=4)
        model, init_fn, _ = llama.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0), seq_len=16)
        rng = np.random.default_rng(0)
        a = rng.integers(1, 512, 16)
        b = a.copy()
        b[0] = (b[0] + 1) % 512    # mutate a token far outside the window
        la = model.apply({"params": params}, jnp.asarray([a], jnp.int32))
        lb = model.apply({"params": params}, jnp.asarray([b], jnp.int32))
        # last position (15) can only see positions 12..15 -> identical
        np.testing.assert_allclose(la[0, -1], lb[0, -1], atol=1e-5)
        assert not np.allclose(la[0, 2], lb[0, 2], atol=1e-4)

    def test_trains_through_engine(self):
        cfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
        model, init_fn, loss_fn = llama.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0), batch_size=4, seq_len=17)
        engine, _, _, _ = dstpu.initialize(
            loss_fn=loss_fn, params=params,
            config={"train_micro_batch_size_per_gpu": 8,
                    "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                    "zero_optimization": {"stage": 2}})
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(12):
            start = rng.integers(0, 40, (engine.train_batch_size_(),))
            toks = (start[:, None] + np.arange(18)[None, :]) % 512
            losses.append(float(engine.train_batch(
                {"tokens": jnp.asarray(toks, jnp.int32)})))
        assert losses[-1] < losses[0]


class TestMixtral:
    def test_forward_and_loss(self):
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32)
        model, init_fn, loss_fn = mixtral.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0))
        assert "moe" in params["layer_0"]
        assert params["layer_0"]["moe"]["wi_gate"].shape[0] == cfg.num_experts
        loss = loss_fn(params,
                       {"tokens": jnp.ones((2, 17), jnp.int32)},
                       jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))

    def test_experts_contribute(self):
        """Zeroing expert weights must change the output."""
        cfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32)
        model, init_fn, _ = mixtral.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0))
        toks = jnp.asarray([[5, 9, 2, 14]], jnp.int32)
        out1 = model.apply({"params": params}, toks, False)
        params2 = jax.tree_util.tree_map(lambda x: x, params)
        params2["layer_0"]["moe"]["wo"] = jnp.zeros_like(
            params2["layer_0"]["moe"]["wo"])
        out2 = model.apply({"params": params2}, toks, False)
        assert not np.allclose(out1, out2, atol=1e-5)


class TestBert:
    def test_mlm_forward_and_mask(self):
        cfg = bert.BertConfig.tiny(dtype=jnp.float32)
        model, init_fn, loss_fn = bert.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0))
        logits = model.apply({"params": params},
                             jnp.zeros((2, 12), jnp.int32))
        assert logits.shape == (2, 12, cfg.vocab_size)
        loss = loss_fn(params, {"tokens": jnp.ones((2, 12), jnp.int32)},
                       jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))

    def test_bidirectional(self):
        """Changing a later token must affect earlier positions (no causal
        mask) — the opposite of the llama test."""
        cfg = bert.BertConfig.tiny(dtype=jnp.float32)
        model, init_fn, _ = bert.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0))
        a = np.ones(10, np.int32) * 5
        b = a.copy()
        b[-1] = 9
        la = model.apply({"params": params}, jnp.asarray([a]))
        lb = model.apply({"params": params}, jnp.asarray([b]))
        assert not np.allclose(la[0, 0], lb[0, 0], atol=1e-5)

    def test_attention_mask_excludes_padding(self):
        cfg = bert.BertConfig.tiny(dtype=jnp.float32)
        model, init_fn, _ = bert.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0))
        toks = np.ones((1, 10), np.int32) * 3
        am = np.ones((1, 10), np.int32)
        am[0, 6:] = 0
        la = model.apply({"params": params}, jnp.asarray(toks),
                         attention_mask=jnp.asarray(am))
        toks2 = toks.copy()
        toks2[0, 7] = 99           # mutate masked-out position
        lb = model.apply({"params": params}, jnp.asarray(toks2),
                         attention_mask=jnp.asarray(am))
        np.testing.assert_allclose(la[0, :6], lb[0, :6], atol=1e-5)


class TestRegistry:
    def test_hf_llama_mapping(self):
        name, cfg = config_from_hf({
            "model_type": "llama", "vocab_size": 1000, "hidden_size": 128,
            "num_hidden_layers": 3, "num_attention_heads": 8,
            "num_key_value_heads": 2, "intermediate_size": 256,
            "rope_theta": 500000.0, "rms_norm_eps": 1e-6})
        assert name == "llama"
        assert cfg.num_kv_heads == 2 and cfg.rope_theta == 500000.0

    def test_hf_mixtral_mapping(self):
        _, cfg = config_from_hf({
            "model_type": "mixtral", "num_local_experts": 4,
            "num_experts_per_tok": 2})
        assert cfg.num_experts == 4 and cfg.experts_top_k == 2

    def test_hf_mistral_qwen(self):
        _, m = config_from_hf({"model_type": "mistral", "sliding_window": 1024})
        assert m.sliding_window == 1024
        _, q = config_from_hf({"model_type": "qwen2"})
        assert q.qkv_bias is True

    def test_unknown_arch_raises(self):
        with pytest.raises(ValueError, match="unknown architecture"):
            get_arch("not_a_model")


class TestLlamaRaggedParity:
    def _setup(self, mcfg):
        cfg = RaggedInferenceConfig(max_seqs=2, chunk_size=8, block_size=4,
                                    num_blocks=64, max_blocks_per_seq=16,
                                    dtype="float32")
        if isinstance(mcfg, mixtral.MixtralConfig):
            model, init_fn, _ = mixtral.make_model(mcfg)
        else:
            model, init_fn, _ = llama.make_model(mcfg)
        params = init_fn(jax.random.PRNGKey(0), seq_len=16)
        return cfg, model, params

    def test_llama_prefill_decode_parity(self):
        mcfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
        cfg, model, params = self._setup(mcfg)
        eng = InferenceEngineV2(mcfg, params, cfg)
        prompt = list(np.random.default_rng(0).integers(1, 512, 13))
        gen = eng.generate([prompt], max_new_tokens=4)[0]
        toks = list(prompt)
        for _ in range(4):
            logits = model.apply({"params": params},
                                 jnp.asarray([toks], jnp.int32))
            nxt = int(jnp.argmax(logits[0, -1]))
            toks.append(nxt)
        assert gen == toks[len(prompt):]

    def test_mixtral_prefill_parity(self):
        mcfg = mixtral.MixtralConfig.tiny(dtype=jnp.float32)
        cfg, model, params = self._setup(mcfg)
        eng = InferenceEngineV2(mcfg, params, cfg)
        prompt = list(np.random.default_rng(1).integers(1, 512, 11))
        out = eng.put([0], [prompt])
        full = model.apply({"params": params},
                           jnp.asarray([prompt], jnp.int32), False)
        np.testing.assert_allclose(out[0], np.asarray(full)[0, -1],
                                   atol=3e-4, rtol=3e-4)


class TestNewArchFamilies:
    """OPT / Falcon / Phi / Phi-3 / Qwen2-MoE — v2 model-zoo breadth
    (reference inference/v2/model_implementations/{opt,falcon,phi,phi3,
    qwen_v2_moe})."""

    @pytest.mark.parametrize("arch", ["opt", "falcon", "phi"])
    def test_forward_and_loss(self, arch):
        from deepspeed_tpu.models.registry import get_arch
        entry = get_arch(arch)
        cfg = entry.config_cls.tiny(dtype=jnp.float32)
        model, init_fn, loss_fn = entry.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=16)
        logits = model.apply({"params": params},
                             jnp.zeros((2, 16), jnp.int32))
        assert logits.shape == (2, 16, cfg.vocab_size)
        loss = loss_fn(params, {"tokens": jnp.ones((2, 17), jnp.int32)},
                       jax.random.PRNGKey(0))
        assert np.isfinite(float(loss))

    def test_falcon_variants(self):
        from deepspeed_tpu.models.falcon import Falcon, FalconConfig
        for kw in ({"parallel_attn": False},
                   {"new_decoder_architecture": True, "num_kv_heads": 2}):
            cfg = FalconConfig.tiny(dtype=jnp.float32, **kw)
            model = Falcon(cfg)
            p = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))["params"]
            out = model.apply({"params": p}, jnp.zeros((1, 8), jnp.int32))
            assert out.shape == (1, 8, cfg.vocab_size)

    def test_phi_partial_rotary(self):
        from deepspeed_tpu.models.phi import PhiConfig
        cfg = PhiConfig.tiny()
        assert 0 < cfg.rotary_dim < cfg.head_dim
        assert cfg.rotary_dim % 2 == 0

    def test_hf_config_mapping(self):
        from deepspeed_tpu.models.registry import config_from_hf
        arch, cfg = config_from_hf({
            "model_type": "opt", "vocab_size": 1000, "ffn_dim": 64,
            "num_hidden_layers": 3})
        assert arch == "opt" and cfg.num_layers == 3 and cfg.ffn_dim == 64
        arch, cfg = config_from_hf({
            "model_type": "falcon", "multi_query": True,
            "num_attention_heads": 8, "hidden_size": 64})
        assert cfg.num_kv_heads == 1
        arch, cfg = config_from_hf({
            "model_type": "phi3", "num_hidden_layers": 4,
            "hidden_size": 64, "num_attention_heads": 4,
            "num_key_value_heads": 2})
        assert arch == "phi3" and cfg.num_kv_heads == 2
        arch, cfg = config_from_hf({
            "model_type": "qwen2_moe", "num_experts": 4,
            "num_hidden_layers": 2, "hidden_size": 32,
            "num_attention_heads": 4, "moe_intermediate_size": 16})
        assert cfg.num_experts == 4 and cfg.intermediate_size == 16

    def test_trains_through_engine(self, devices8):
        from deepspeed_tpu.models.registry import get_arch
        import deepspeed_tpu as dstpu
        entry = get_arch("opt")
        cfg = entry.config_cls.tiny(dtype=jnp.float32)
        model, init_fn, loss_fn = entry.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=16)
        engine, _, _, _ = dstpu.initialize(
            loss_fn=loss_fn, params=params, config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                "zero_optimization": {"stage": 2}})
        losses = []
        rng = np.random.RandomState(0)
        for i in range(6):
            starts = rng.randint(0, 512, size=(16,))
            seq = (starts[:, None] + np.arange(17)[None, :]) % 512
            losses.append(float(engine.train_batch(
                {"tokens": jnp.asarray(seq, jnp.int32)})))
        assert losses[-1] < losses[0]

    def test_opt_350m_and_falcon_alibi_variants(self):
        from deepspeed_tpu.models.opt import OPT, OPTConfig
        from deepspeed_tpu.models.falcon import Falcon, FalconConfig
        cfg = OPTConfig.tiny(dtype=jnp.float32, do_layer_norm_before=False,
                             word_embed_proj_dim=32)
        model = OPT(cfg)
        p = model.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 8), jnp.int32))["params"]
        assert "project_in" in p and "project_out" in p
        out = model.apply({"params": p}, jnp.zeros((1, 8), jnp.int32))
        assert out.shape == (1, 8, cfg.vocab_size)

        fcfg = FalconConfig.tiny(dtype=jnp.float32, alibi=True)
        fmodel = Falcon(fcfg)
        fp = fmodel.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))["params"]
        out = fmodel.apply({"params": fp}, jnp.zeros((1, 8), jnp.int32))
        assert np.isfinite(np.asarray(out)).all()

        from deepspeed_tpu.models.registry import config_from_hf
        _, c = config_from_hf({"model_type": "opt", "hidden_size": 64,
                               "word_embed_proj_dim": 32,
                               "do_layer_norm_before": False})
        assert c.word_embed_proj_dim == 32 and not c.do_layer_norm_before
        _, c = config_from_hf({"model_type": "falcon", "alibi": True,
                               "num_attention_heads": 4, "hidden_size": 64})
        assert c.alibi


def test_bloom_neox_gptj_train():
    """The three new v1-injection-breadth families train (loss drops)."""
    from deepspeed_tpu.models.bloom import BloomConfig
    from deepspeed_tpu.models.bloom import make_model as make_bloom
    from deepspeed_tpu.models.gpt_neox import (GPTJConfig, GPTNeoXConfig,
                                               make_model_gptj,
                                               make_model_neox)
    import deepspeed_tpu as dstpu

    for make, cfg in [
            (make_bloom, BloomConfig.tiny(dtype=jnp.float32)),
            (make_model_neox, GPTNeoXConfig.tiny(dtype=jnp.float32)),
            (make_model_gptj, GPTJConfig.tiny(dtype=jnp.float32))]:
        model, init_fn, loss_fn = make(cfg)
        params = init_fn(jax.random.PRNGKey(0), batch_size=4, seq_len=16)
        engine, _, _, _ = dstpu.initialize(
            loss_fn=loss_fn, params=params,
            config={"train_micro_batch_size_per_gpu": 4,
                    "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                    "steps_per_print": 10_000})
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(8):
            st = rng.integers(0, 64, size=(engine.config.train_batch_size,))
            seq = (st[:, None] + np.arange(17)[None, :]) % 64
            losses.append(float(engine.train_batch(
                {"tokens": jnp.asarray(seq, jnp.int32)})))
        assert losses[-1] < losses[0], f"{type(cfg).__name__}: {losses}"


class TestLlamaChunkedLoss:
    def test_loss_matches_full_logits(self):
        # make_model's loss fuses the LM head into chunked_lm_xent; it
        # must equal the full-logits log_softmax NLL for BOTH head modes
        from deepspeed_tpu.models import llama
        for tie in (True, False):
            cfg = llama.LlamaConfig(
                vocab_size=64, max_seq_len=33, num_layers=2, num_heads=2,
                num_kv_heads=1, hidden_size=32, intermediate_size=64,
                dtype=jnp.float32, tie_embeddings=tie)
            model, init_fn, loss_fn = llama.make_model(cfg)
            params = init_fn(jax.random.PRNGKey(0), batch_size=2,
                             seq_len=16)
            toks = jnp.asarray(
                np.random.RandomState(0).randint(0, 64, (2, 17)),
                jnp.int32)
            logits = model.apply({"params": params}, toks[:, :-1])
            logp = jax.nn.log_softmax(logits, axis=-1)
            want = float(-jnp.take_along_axis(
                logp, toks[:, 1:][..., None], axis=-1)[..., 0].mean())
            got = float(loss_fn(params, {"tokens": toks}, None))
            assert abs(want - got) < 1e-5


class TestMixtralChunkedLoss:
    def test_loss_matches_full_logits(self):
        # mixtral's fused-head loss == full-logits NLL + router aux
        from deepspeed_tpu.models import mixtral as mx
        cfg = mx.MixtralConfig(
            vocab_size=64, max_seq_len=33, num_layers=2, num_heads=2,
            num_kv_heads=1, hidden_size=32, intermediate_size=64,
            num_experts=4, experts_top_k=2, dtype=jnp.float32)
        model, init_fn, loss_fn = mx.make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=16)
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 64, (2, 17)), jnp.int32)
        rng = jax.random.PRNGKey(2)
        logits, aux = model.apply({"params": params}, toks[:, :-1],
                                  rngs={"gating": rng},
                                  mutable=["losses"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = float(-jnp.take_along_axis(
            logp, toks[:, 1:][..., None], axis=-1)[..., 0].mean())
        moe = float(sum(jnp.sum(v) for v in
                        jax.tree_util.tree_leaves(aux.get("losses", {}))))
        want = nll + cfg.router_aux_loss_coef * moe
        got = float(loss_fn(params, {"tokens": toks}, rng))
        assert abs(want - got) < 1e-5
