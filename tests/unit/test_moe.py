"""MoE tests — analogue of reference tests/unit/moe/test_moe.py: gating
semantics (capacity, drop, aux loss), EP dispatch parity, PR-MoE."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.config import MeshConfig
from deepspeed_tpu.moe import MoE, capacity, top1gating, top2gating, topkgating
from deepspeed_tpu.parallel import build_mesh


def _logits(S=16, E=4, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (S, E), jnp.float32)


# ------------------------------- gating ------------------------------- #

def test_capacity_formula():
    assert capacity(16, 4, 1.0, 1) == 4
    assert capacity(16, 4, 2.0, 1) == 8
    assert capacity(16, 4, 0.1, 4) == 4     # min_capacity floor


def test_top1_shapes_and_onehot():
    l_aux, combine, dispatch = top1gating(_logits(), capacity_factor=2.0)
    S, E, C = combine.shape
    assert (S, E) == (16, 4) and C == 8
    # each token routed to at most one (expert, slot)
    assert np.all(np.asarray(dispatch).sum(axis=(1, 2)) <= 1)
    assert float(l_aux) > 0


def test_top1_capacity_drop():
    # all tokens pick expert 0 -> only C survive
    logits = jnp.zeros((16, 4)).at[:, 0].set(10.0)
    _, _, dispatch = top1gating(logits, capacity_factor=1.0, min_capacity=1)
    kept = np.asarray(dispatch).sum()
    assert kept == 4    # C = 16/4 * 1.0


def test_top1_no_drop():
    logits = jnp.zeros((16, 4)).at[:, 0].set(10.0)
    _, _, dispatch = top1gating(logits, capacity_factor=1.0, drop_tokens=False)
    assert np.asarray(dispatch).sum() == 16


def test_top2_two_experts_per_token():
    _, combine, dispatch = top2gating(_logits(), capacity_factor=2.0)
    per_token = np.asarray(dispatch).sum(axis=(1, 2))
    assert np.all(per_token == 2)
    # combine weights normalized over the two picks
    np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                               np.ones(16), rtol=1e-5)


def test_top2_norm_topk_prob_off():
    """normalize_weights=False keeps full-softmax weights (HF qwen2-moe
    norm_topk_prob=False): combine weights are the raw softmax probs of the
    two picks, so they sum to < 1 per token."""
    logits = _logits()
    gates = np.asarray(jax.nn.softmax(logits, axis=-1))
    _, combine, dispatch = top2gating(logits, capacity_factor=2.0,
                                      normalize_weights=False)
    combine = np.asarray(combine)
    picked = np.asarray(dispatch).astype(np.float32)
    # each kept (expert, slot) weight equals the raw softmax prob
    per_expert_w = combine.sum(axis=2)          # [S, E]
    per_expert_m = picked.sum(axis=2)           # [S, E]
    np.testing.assert_allclose(per_expert_w, gates * per_expert_m, rtol=1e-5)
    assert np.all(combine.sum(axis=(1, 2)) < 1.0)


def test_topk_matches_k():
    _, _, dispatch = topkgating(_logits(S=32, E=8), k=3, capacity_factor=3.0)
    per_token = np.asarray(dispatch).sum(axis=(1, 2))
    assert np.all(per_token == 3)


def test_rts_gumbel_changes_selection():
    logits = _logits(S=64, E=8, seed=1)
    _, _, d1 = top1gating(logits, capacity_factor=8.0)
    _, _, d2 = top1gating(logits, capacity_factor=8.0,
                          rng=jax.random.PRNGKey(7), noisy_gate_policy="RSample")
    assert not np.array_equal(np.asarray(d1), np.asarray(d2))


# ------------------------------- layer -------------------------------- #

def _run_layer(ep_mesh=None, use_residual=False, k=1, seed=0, x=None):
    layer = MoE(d_model=16, num_experts=4, k=k, hidden=32,
                capacity_factor=4.0, ep_mesh=ep_mesh, use_residual=use_residual)
    if x is None:
        x = jax.random.normal(jax.random.PRNGKey(seed), (4, 8, 16), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(0), x)
    (out, l_aux) = layer.apply(variables, x)
    return np.asarray(out), float(l_aux), variables


def test_moe_layer_forward():
    out, l_aux, _ = _run_layer()
    assert out.shape == (4, 8, 16)
    assert np.isfinite(out).all() and l_aux > 0


def test_grouped_gemm_matches_dropless_capacity():
    """grouped_moe_ffn (sorted ragged_dot, S*k expert rows) must match the
    capacity einsum path with drop_tokens=False (C=S: nothing dropped) —
    the reference's CUTLASS grouped-GEMM capability class
    (inference/v2/kernels/cutlass_ops/moe_gemm/)."""
    from deepspeed_tpu.moe.layer import MoE
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 6, 16), jnp.float32)
    kw = dict(d_model=16, num_experts=4, k=2, hidden=32,
              drop_tokens=False, gated=True,
              top2_2nd_expert_sampling=False,
              activation=jax.nn.silu)
    ref_layer = MoE(**kw, use_grouped_gemm=False)
    variables = ref_layer.init(jax.random.PRNGKey(0), x)
    ref, _ = ref_layer.apply(variables, x)
    got, _ = MoE(**kw, use_grouped_gemm=True).apply(variables, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_grouped_gemm_matches_dropless_capacity_k1():
    """k=1: the combine weight must be the router's softmax prob
    (top1gating semantics), not a renormalized constant 1.0."""
    from deepspeed_tpu.moe.layer import MoE
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 6, 16), jnp.float32)
    kw = dict(d_model=16, num_experts=4, k=1, hidden=32,
              drop_tokens=False, gated=True, activation=jax.nn.silu)
    variables = MoE(**kw, use_grouped_gemm=False).init(
        jax.random.PRNGKey(0), x)
    ref, _ = MoE(**kw, use_grouped_gemm=False).apply(variables, x)
    got, _ = MoE(**kw, use_grouped_gemm=True).apply(variables, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_grouped_gemm_rejects_stochastic_gating():
    from deepspeed_tpu.moe.layer import MoE
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 6, 16), jnp.float32)
    layer = MoE(d_model=16, num_experts=4, k=2, hidden=32,
                drop_tokens=False, gated=True, use_grouped_gemm=True,
                activation=jax.nn.silu)   # top2 sampling default ON
    with pytest.raises(ValueError, match="deterministically"):
        layer.init(jax.random.PRNGKey(0), x)
    # auto mode silently keeps the sampling capacity path instead
    auto = MoE(d_model=16, num_experts=4, k=2, hidden=32, drop_tokens=False,
               gated=True, activation=jax.nn.silu)
    v = auto.init(jax.random.PRNGKey(0), x)
    out, _ = auto.apply(v, x, rngs={"gating": jax.random.PRNGKey(1)})
    assert np.isfinite(np.asarray(out)).all()


def test_grouped_gemm_grad_flows():
    from deepspeed_tpu.moe.layer import MoE
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 6, 16), jnp.float32)
    layer = MoE(d_model=16, num_experts=4, k=2, hidden=32,
                drop_tokens=False, gated=True, use_grouped_gemm=True,
                top2_2nd_expert_sampling=False,
                activation=jax.nn.silu)
    variables = layer.init(jax.random.PRNGKey(0), x)

    def loss(v):
        out, l_aux = layer.apply(v, x)
        return (out ** 2).mean() + 0.01 * l_aux

    g = jax.grad(loss)(variables)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(leaf)).all() for leaf in leaves)
    assert any(float(np.abs(np.asarray(leaf)).sum()) > 0 for leaf in leaves)


def test_grouped_gemm_computes_only_routed_rows():
    """The grouped dispatch feeds ragged_dot exactly S*k rows (the routed
    tokens), not S*E — assert via the jaxpr's ragged_dot operand shape."""
    from deepspeed_tpu.moe.sharded_moe import grouped_moe_ffn
    S, M, H, E, k = 10, 8, 16, 5, 2
    tok = jnp.ones((S, M)); lg = jnp.ones((S, E))
    ws = (jnp.ones((E, M, H)), jnp.ones((E, M, H)), jnp.ones((E, H, M)))
    jaxpr = jax.make_jaxpr(
        lambda t: grouped_moe_ffn(t, lg, k, ws, jax.nn.silu,
                                  jnp.float32))(tok)
    rdots = [e for e in jaxpr.jaxpr.eqns if "ragged" in str(e.primitive)]
    assert rdots, "expected ragged_dot in the grouped path"
    for e in rdots:
        assert e.invars[0].aval.shape[0] == S * k      # routed rows only


def test_moe_residual():
    out, l_aux, variables = _run_layer(use_residual=True)
    assert out.shape == (4, 8, 16)
    assert "residual_fc1" in variables["params"]
    assert "coefficient" in variables["params"]


def test_moe_ep_matches_single_group(devices8):
    """Expert-parallel (a2a over 4 expert devices) must equal the ep=1 path
    when each device group sees the same tokens it would locally."""
    topo = build_mesh(MeshConfig(expert=4, data=2))
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 8, 16), jnp.float32)

    layer_ep = MoE(d_model=16, num_experts=4, hidden=32, capacity_factor=4.0,
                   ep_mesh=topo.mesh)
    variables = layer_ep.init(jax.random.PRNGKey(0), x)
    out_ep, aux_ep = layer_ep.apply(variables, x)

    # reference: same weights, no EP — but routed per (data,expert) group of
    # the flattened tokens, exactly as the sharded path groups them
    layer_1 = MoE(d_model=16, num_experts=4, hidden=32, capacity_factor=4.0)
    S = 8 * 8
    groups = 8  # data*expert devices
    tokens = np.asarray(x).reshape(S, 16)
    outs = []
    for g in range(groups):
        xg = tokens[g * (S // groups):(g + 1) * (S // groups)]
        xg = jnp.asarray(xg).reshape(1, S // groups, 16)
        og, _ = layer_1.apply(variables, xg)
        outs.append(np.asarray(og).reshape(-1, 16))
    ref = np.concatenate(outs).reshape(8, 8, 16)
    np.testing.assert_allclose(np.asarray(out_ep), ref, atol=1e-5)


def test_moe_ep_grad_flows(devices8):
    topo = build_mesh(MeshConfig(expert=2, data=4))
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 4, 16), jnp.float32)
    layer = MoE(d_model=16, num_experts=4, hidden=32, capacity_factor=4.0,
                ep_mesh=topo.mesh)
    variables = layer.init(jax.random.PRNGKey(0), x)

    def loss(v):
        out, l_aux = layer.apply(v, x)
        return (out ** 2).mean() + 0.01 * l_aux

    g = jax.grad(loss)(variables)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    assert any(float(jnp.abs(l).max()) > 0 for l in leaves)


def test_moe_invalid_expert_split():
    topo = build_mesh(MeshConfig(expert=4, data=2))
    layer = MoE(d_model=16, num_experts=6, ep_mesh=topo.mesh)
    x = jnp.ones((4, 4, 16))
    with pytest.raises(ValueError):
        layer.init(jax.random.PRNGKey(0), x)


def test_qwen2_moe_shared_expert():
    """qwen2-moe: the always-on shared expert contributes and trains
    (reference v2 qwen_v2_moe containers)."""
    import numpy as np
    from deepspeed_tpu.models.mixtral import Mixtral, MixtralConfig, make_model
    from deepspeed_tpu.models.registry import config_from_hf
    arch, cfg = config_from_hf({
        "model_type": "qwen2_moe", "vocab_size": 64, "hidden_size": 32,
        "num_hidden_layers": 1, "num_attention_heads": 2,
        "num_key_value_heads": 2, "moe_intermediate_size": 16,
        "num_experts": 4, "num_experts_per_tok": 2,
        "shared_expert_intermediate_size": 24,
        "max_position_embeddings": 64})
    assert cfg.shared_expert_size == 24
    import dataclasses
    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32,
                              attention_impl="xla")
    model, init_fn, loss_fn = make_model(cfg)
    params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
    layer = params["layer_0"]
    assert "shared_gate_proj" in layer and "shared_expert_gate" in layer
    loss = float(loss_fn(params, {"tokens": jnp.ones((2, 9), jnp.int32)},
                         jax.random.PRNGKey(0)))
    assert np.isfinite(loss)
    # the shared expert changes outputs (zeroing it perturbs the loss)
    zeroed = jax.tree_util.tree_map(lambda x: x, params)
    zeroed["layer_0"] = dict(zeroed["layer_0"])
    zeroed["layer_0"]["shared_down_proj"] = {
        "kernel": jnp.zeros_like(layer["shared_down_proj"]["kernel"])}
    loss2 = float(loss_fn(zeroed, {"tokens": jnp.ones((2, 9), jnp.int32)},
                          jax.random.PRNGKey(0)))
    assert loss != loss2


# ------------------ EP orderings + experts-TP + ZeRO-2 ----------------- #

def test_expert_placement_orderings(devices8):
    """Reference groups.py:117/:188 parity: 'inside_data' makes an expert
    group CONTIGUOUS device ids, 'outside_data' strides them across data."""
    t_in = build_mesh(MeshConfig(expert=2, data=4,
                                 expert_placement="inside_data"))
    dev = np.vectorize(lambda d: d.id)(t_in.mesh.devices)
    # order (pipe, data, expert, seq, model) -> shape (1,4,2,1,1)
    groups_in = dev.reshape(4, 2)
    assert all(g[1] - g[0] == 1 for g in groups_in)      # contiguous

    from deepspeed_tpu.parallel import topology as topo_mod
    topo_mod._TOPOLOGY = None
    t_out = build_mesh(MeshConfig(expert=2, data=4,
                                  expert_placement="outside_data"))
    dev = np.vectorize(lambda d: d.id)(t_out.mesh.devices)
    # order (pipe, expert, data, seq, model) -> shape (1,2,4,1,1)
    groups_out = dev.reshape(2, 4)
    # an expert group = devices with the same data coord -> stride 4
    assert groups_out[1, 0] - groups_out[0, 0] == 4


@pytest.mark.parametrize("placement", ["inside_data", "outside_data"])
def test_moe_ep_both_orderings_run(devices8, placement):
    from deepspeed_tpu.parallel import topology as topo_mod
    topo_mod._TOPOLOGY = None
    topo = build_mesh(MeshConfig(expert=2, data=4,
                                 expert_placement=placement))
    out, l_aux, _ = _run_layer(ep_mesh=topo.mesh,
                               x=jax.random.normal(jax.random.PRNGKey(3),
                                                   (8, 8, 16), jnp.float32))
    assert np.isfinite(out).all() and l_aux > 0


@pytest.mark.parametrize("gated", [False, True])
def test_experts_tp_matches_plain(devices8, gated):
    """Experts-TP (hidden dim over the model axis, psum after wo —
    reference moe/mappings.py capability) must match the unsharded layer."""
    topo = build_mesh(MeshConfig(expert=2, data=2, model=2))
    x = jax.random.normal(jax.random.PRNGKey(5), (8, 8, 16), jnp.float32)

    kw = dict(d_model=16, num_experts=4, hidden=32, capacity_factor=4.0,
              gated=gated)
    layer_tp = MoE(ep_mesh=topo.mesh, expert_tensor_parallel=True, **kw)
    variables = layer_tp.init(jax.random.PRNGKey(0), x)
    out_tp, aux_tp = layer_tp.apply(variables, x)

    layer_ep = MoE(ep_mesh=topo.mesh, **kw)
    out_ep, aux_ep = layer_ep.apply(variables, x)

    np.testing.assert_allclose(np.asarray(out_tp), np.asarray(out_ep),
                               atol=1e-5, rtol=1e-4)
    assert abs(float(aux_tp) - float(aux_ep)) < 1e-6


def test_moe_ep_zero2_trains(devices8):
    """EP x ZeRO-2: a Mixtral-tiny trains through the engine on an
    expert-bearing mesh with stage-2 grad/opt sharding over data."""
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.mixtral import Mixtral, MixtralConfig

    topo = build_mesh(MeshConfig(expert=2, data=4))
    cfg = MixtralConfig.tiny(dtype=jnp.float32)
    model = Mixtral(cfg, topo.mesh)

    def loss_fn(params, batch, rng):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = model.apply(
            {"params": params}, inputs, train=True,
            rngs={"gating": rng})
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, targets[..., None], -1).mean()

    params = model.init(
        {"params": jax.random.PRNGKey(0), "gating": jax.random.PRNGKey(1)},
        jnp.zeros((2, 16), jnp.int32))["params"]
    engine, _, _, _ = dstpu.initialize(
        loss_fn=loss_fn, params=params, topology=topo,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 10_000})
    B = engine.config.train_batch_size
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(6):
        st = rng.integers(0, 48, size=(B,))
        seq = (st[:, None] + np.arange(17)[None, :]) % 64
        losses.append(float(engine.train_batch(
            {"tokens": jnp.asarray(seq, jnp.int32)})))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


# -------------- grouped GEMM under expert parallelism ------------------ #

def test_moe_ep_grouped_matches_capacity(devices8):
    """VERDICT r3 #5: the grouped (a2a + ragged_dot) EP path must match the
    EP capacity-einsum path with drop_tokens=False (C=S: nothing dropped)
    under expert=2, for gated top-2 experts."""
    topo = build_mesh(MeshConfig(expert=2, data=4))
    x = jax.random.normal(jax.random.PRNGKey(7), (8, 8, 16), jnp.float32)
    kw = dict(d_model=16, num_experts=4, k=2, hidden=32,
              drop_tokens=False, gated=True,
              top2_2nd_expert_sampling=False, activation=jax.nn.silu,
              ep_mesh=topo.mesh)
    ref_layer = MoE(**kw, use_grouped_gemm=False)
    variables = ref_layer.init(jax.random.PRNGKey(0), x)
    ref, _ = ref_layer.apply(variables, x)
    # strict-dropless slot capacity (factor == ep) for exact parity
    got, _ = MoE(**kw, use_grouped_gemm=True,
                 ep_grouped_capacity_factor=2.0 * 2).apply(variables, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_moe_ep_grouped_k1_and_auxloss(devices8):
    """k=1 EP grouped: combine weight is the softmax prob; l_aux matches
    the capacity path's first-choice statistic."""
    topo = build_mesh(MeshConfig(expert=2, data=4))
    x = jax.random.normal(jax.random.PRNGKey(9), (8, 8, 16), jnp.float32)
    kw = dict(d_model=16, num_experts=4, k=1, hidden=32,
              drop_tokens=False, gated=False, activation=jax.nn.gelu,
              ep_mesh=topo.mesh)
    ref_layer = MoE(**kw, use_grouped_gemm=False)
    variables = ref_layer.init(jax.random.PRNGKey(0), x)
    ref, aux_ref = ref_layer.apply(variables, x)
    got, aux_got = MoE(**kw, use_grouped_gemm=True,
                       ep_grouped_capacity_factor=4.0).apply(variables, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(float(aux_got), float(aux_ref), rtol=1e-5)


def test_moe_ep_grouped_feeds_ragged_dot(devices8):
    """The EP grouped path lowers to ragged_dot over the a2a'd rows (not
    the [S, E, C] capacity einsum)."""
    topo = build_mesh(MeshConfig(expert=2, data=4))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, 16), jnp.float32)
    layer = MoE(d_model=16, num_experts=4, k=2, hidden=32,
                drop_tokens=False, gated=True,
                top2_2nd_expert_sampling=False, activation=jax.nn.silu,
                ep_mesh=topo.mesh, use_grouped_gemm=True)
    variables = layer.init(jax.random.PRNGKey(0), x)
    txt = jax.make_jaxpr(lambda v: layer.apply(v, x))(variables).pretty_print()
    assert "ragged_dot" in txt
    assert "all_to_all" in txt


def test_moe_ep_grouped_grad_flows(devices8):
    topo = build_mesh(MeshConfig(expert=2, data=4))
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 4, 16), jnp.float32)
    layer = MoE(d_model=16, num_experts=4, k=2, hidden=32,
                drop_tokens=False, gated=True,
                top2_2nd_expert_sampling=False, activation=jax.nn.silu,
                ep_mesh=topo.mesh, use_grouped_gemm=True)
    variables = layer.init(jax.random.PRNGKey(0), x)

    def loss(v):
        out, l_aux = layer.apply(v, x)
        return (out ** 2).mean() + 0.01 * l_aux

    g = jax.jit(jax.grad(loss))(variables)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    # expert weights receive gradient through the a2a round-trip
    assert float(jnp.abs(g["params"]["wi_gate"]).max()) > 0


def test_moe_ep_grouped_with_experts_tp(devices8):
    """EP x experts-TP: hidden-sharded ragged_dot with one psum before the
    return a2a must still match the capacity path."""
    topo = build_mesh(MeshConfig(expert=2, model=2, data=2))
    x = jax.random.normal(jax.random.PRNGKey(4), (4, 8, 16), jnp.float32)
    kw = dict(d_model=16, num_experts=4, k=2, hidden=32,
              drop_tokens=False, gated=True,
              top2_2nd_expert_sampling=False, activation=jax.nn.silu,
              ep_mesh=topo.mesh, expert_tensor_parallel=True)
    ref_layer = MoE(**kw, use_grouped_gemm=False)
    variables = ref_layer.init(jax.random.PRNGKey(0), x)
    ref, _ = ref_layer.apply(variables, x)
    got, _ = MoE(**kw, use_grouped_gemm=True,
                 ep_grouped_capacity_factor=4.0).apply(variables, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)
