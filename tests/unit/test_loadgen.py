"""Open-loop loadgen tests (ISSUE 10): seeded arrival determinism, the
never-back-pressured arrival clock under a deliberately saturated
engine, the tier-1 capacity smoke (tiny model, 2 offered rates, goodput
+ parity gated), arrival-anchored engine admission hooks, and the
warm-path 0-fresh-compiles gate under loadgen traffic.

One tiny GPT-2 engine (module fixture) serves every driver test; the
saturation test builds its own starved-pool engine."""

import json

import numpy as np
import pytest

from deepspeed_tpu.telemetry.loadgen import (PoissonArrivals,
                                             TraceArrivals,
                                             UniformArrivals,
                                             WorkloadMix, _tiny_engine,
                                             build_requests,
                                             run_open_loop,
                                             sweep_capacity)

# ------------------------------------------------------------------ #
# arrival processes + workload mix: pure, seeded, deterministic
# ------------------------------------------------------------------ #


class TestArrivalDeterminism:
    def test_poisson_seed_determinism(self):
        a = PoissonArrivals(20.0, seed=7).schedule(200)
        b = PoissonArrivals(20.0, seed=7).schedule(200)
        assert np.array_equal(a, b)
        assert not np.array_equal(
            a, PoissonArrivals(20.0, seed=8).schedule(200))
        # memoryless gaps at the configured mean rate
        gaps = np.diff(a)
        assert (gaps > 0).all()
        assert abs(gaps.mean() - 1 / 20.0) < 0.015

    def test_uniform_spacing(self):
        s = UniformArrivals(4.0).schedule(8)
        assert np.allclose(np.diff(s), 0.25)
        assert s[0] == pytest.approx(0.25)

    def test_trace_replay(self, tmp_path):
        raw = [100.5, 100.0, 101.25]          # unsorted, absolute
        t = TraceArrivals(raw)
        assert np.allclose(t.schedule(3), [0.0, 0.5, 1.25])
        assert np.allclose(TraceArrivals(raw, time_scale=0.5)
                           .schedule(3), [0.0, 0.25, 0.625])
        with pytest.raises(ValueError):
            t.schedule(4)                     # trace exhausted -> loud
        path = tmp_path / "trace.json"
        path.write_text(json.dumps({"arrivals": raw}))
        t2 = TraceArrivals.from_file(str(path))
        assert np.allclose(t2.schedule(3), t.schedule(3))

    def test_mix_determinism_and_fractions(self):
        mix = WorkloadMix(prompt_lens=(8, 16), prompt_probs=(0.5, 0.5),
                          gen_lens=(4,), gen_probs=(1.0,),
                          shared_prefix_frac=0.5, shared_prefix_len=6,
                          deadline_frac=0.25, deadline_s=1.0,
                          vocab_size=96)
        proc = PoissonArrivals(50.0, seed=3)
        a = build_requests(proc, mix, 400, seed=3)
        b = build_requests(PoissonArrivals(50.0, seed=3), mix, 400,
                           seed=3)
        assert [(r.uid, r.arrival_s, r.prompt, r.gen_len, r.deadline_s)
                for r in a] == \
               [(r.uid, r.arrival_s, r.prompt, r.gen_len, r.deadline_s)
                for r in b]
        prefix = a[0].prompt[:6] if len(a[0].prompt) > 8 else None
        shared = [r for r in a if len(r.prompt) == 16]
        with_prefix = sum(
            1 for r in shared for p in [r.prompt[:6]]
            if sum(1 for o in shared if o.prompt[:6] == p) > 1)
        assert with_prefix > 0                # the shared preamble hit
        deadlined = sum(1 for r in a if r.deadline_s is not None)
        assert 0.15 < deadlined / 400 < 0.35  # ~deadline_frac
        # arrival schedule is the process's, untouched by the mix
        assert np.allclose([r.arrival_s for r in a],
                           proc.schedule(400))

    def test_moe_decode_heavy_mix_shape(self):
        # the EP serving mix (ISSUE 20): short prompts, long decodes —
        # rungs fixed, token ids inside the tiny-Mixtral vocab,
        # deterministic under (mix, seed)
        mix = WorkloadMix.moe_decode_heavy(vocab_size=96)
        reqs = build_requests(PoissonArrivals(50.0, seed=4), mix, 200,
                              seed=4)
        assert {len(r.prompt) for r in reqs} <= {8, 16}
        assert {r.gen_len for r in reqs} <= {24, 48}
        assert set(mix.prompt_lens) == {8, 16}
        assert set(mix.gen_lens) == {24, 48}
        assert all(0 < t < 96 for r in reqs for t in r.prompt)
        again = build_requests(PoissonArrivals(50.0, seed=4), mix, 200,
                               seed=4)
        assert [r.prompt for r in reqs] == [r.prompt for r in again]


# ------------------------------------------------------------------ #
# driver on a real engine
# ------------------------------------------------------------------ #


def _mix(gen=6, **kw):
    return WorkloadMix(prompt_lens=(12,), prompt_probs=(1.0,),
                       gen_lens=(gen,), gen_probs=(1.0,),
                       shared_prefix_frac=0.5, shared_prefix_len=8,
                       vocab_size=96, **kw)


def _warm(engine, gen=6):
    """One throwaway pass so compiles never land inside a measured
    wall-clock window (tests must hold under any pytest ordering)."""
    reqs = build_requests(PoissonArrivals(200.0, seed=99), _mix(gen),
                          2, seed=99, uid_base=99_000_000)
    run_open_loop(engine, reqs, decode_burst=4)


@pytest.fixture(scope="module")
def eng():
    engine, _ = _tiny_engine()
    _warm(engine)
    return engine


class TestOpenLoop:
    def test_sustainable_rate_completes_everything(self, eng):
        reqs = build_requests(PoissonArrivals(40.0, seed=1), _mix(), 8,
                              seed=1, uid_base=1_000_000)
        res = run_open_loop(eng, reqs, decode_burst=4)
        rep = res.report
        assert rep["requests"]["offered"] == 8
        assert rep["requests"]["completed"] == 8
        assert rep["goodput_frac"] == 1.0
        assert all(len(res.streams[r.uid]) == r.gen_len for r in reqs)
        # latency from the per-request registry stamps, all present
        assert rep["latency_source"] == "registry_stamps"
        assert rep["latency"]["ttft_s"]["count"] == 8
        assert rep["latency"]["queue_wait_s"]["count"] == 8
        assert rep["latency"]["ttft_s"]["p50"] > 0

    def test_parity_instrumentation_on_vs_off(self, eng):
        """Acceptance (ISSUE 10): per-request token streams under
        loadgen are identical with instrumentation on vs off — request
        identity is (mix, seed, index), greedy decode is deterministic
        per request, and the observer toggle changes nothing."""
        reqs = build_requests(PoissonArrivals(60.0, seed=2), _mix(), 8,
                              seed=2, uid_base=2_000_000)
        on = run_open_loop(eng, reqs, decode_burst=4)
        obs = eng._obs
        eng._obs = None
        try:
            off = run_open_loop(eng, reqs, decode_burst=4)
        finally:
            eng._obs = obs
        assert on.streams == off.streams
        assert on.streams and all(on.streams.values())
        # uninstrumented pass still reports (driver-observed fallback)
        assert off.report["latency_source"] == "driver_observed"
        assert off.report["requests"]["completed"] == 8

    def test_warm_loadgen_pass_is_compile_free(self, eng):
        """Acceptance: audited serve programs stay warm under loadgen
        traffic — 0 fresh compiles on a pass over already-seen
        shapes."""
        from deepspeed_tpu.analysis import RecompileTripwire
        reqs = build_requests(PoissonArrivals(60.0, seed=3), _mix(), 6,
                              seed=3, uid_base=3_000_000)
        tw = RecompileTripwire()
        with tw:
            res = run_open_loop(eng, reqs, decode_burst=4)
        assert res.report["requests"]["completed"] == 6
        assert tw.fresh_compiles == 0

    def test_capacity_smoke_two_rates(self, eng):
        """Tier-1 capacity smoke (ISSUE 10 satellite): tiny model, 2
        offered rates — a sustainable rate meeting the goodput SLO and
        a saturating one whose completion rate decouples from the
        offered rate (the open-loop signature a closed loop cannot
        show)."""
        out = sweep_capacity(eng, [4.0, 5000.0], 10,
                             _mix(deadline_frac=1.0, deadline_s=8.0),
                             seed=11, goodput_slo_frac=0.9,
                             decode_burst=4)
        assert len(out["curve"]) == 2
        low, high = out["curve"]
        assert low["goodput_frac"] is not None
        assert low["goodput_frac"] >= 0.9
        assert out["knee_rps"] is not None and out["knee_rps"] >= 4.0
        # saturation: completions cannot track a 5000 rps offer (the
        # open-loop signature; a closed loop would report offered ==
        # completed by construction)
        assert high["completed_rps"] < 0.5 * high["offered_rps"]
        assert abs(low["completed_rps"] - low["offered_rps"]) \
            < 0.5 * low["offered_rps"]

    def test_open_loop_clock_never_back_pressured(self):
        """The tentpole invariant, on a deliberately saturated engine
        (starved pool + deadlines): every request is OFFERED on the
        precomputed schedule — offer lag stays bounded by one
        admit/burst iteration, far below the time the engine needs to
        drain the work — and the overload surfaces as shed/deadline
        outcomes, never as a stalled generator."""
        # pool of 8 blocks with 4-block requests: at most 2 run
        # concurrently, the rest pause-thrash — drain time far exceeds
        # the 0.25 s deadlines, so overload MUST surface as outcomes
        engine, _ = _tiny_engine(max_seqs=2, num_blocks=8,
                                 block_size=16)
        _warm(engine, gen=40)     # compiles must not inflate lag/drain
        mix = _mix(gen=40, deadline_frac=1.0, deadline_s=0.25)
        reqs = build_requests(PoissonArrivals(300.0, seed=5), mix, 16,
                              seed=5, uid_base=5_000_000)
        res = run_open_loop(engine, reqs, decode_burst=4)
        rep = res.report
        r = rep["requests"]
        assert r["offered"] == 16              # nothing stalled/stuck
        # offered rate is schedule-set, far above what completed
        assert rep["rates_rps"]["offered"] > 2 * (
            rep["rates_rps"]["completed"] or 0.0)
        # overload became explicit outcomes, and the books balance
        bad = (r["shed"] + r["deadline_expired"] + r["shed_late"]
               + r["rejected_draining"] + r["rejected_other"])
        assert bad > 0
        assert r["completed"] + bad == 16
        assert rep["goodput_frac"] < 1.0
        # the generator never waited on completions: every offer lags
        # its scheduled time by at most ONE admit/burst iteration on
        # the warmed tiny engine (generously bounded at 2.5 s — a
        # single burst can take over a second on a loaded single-core
        # CI box) — serving this workload to completion at 2-way
        # concurrency takes many seconds, so a completion-gated
        # (closed-loop) generator could not meet this bound
        assert rep["open_loop"]["max_offer_lag_s"] < 2.5
        # and the run's clock covered the whole offer schedule
        assert rep["duration_s"] >= reqs[-1].arrival_s

    def test_max_live_holds_door_without_stalling_clock(self, eng):
        reqs = build_requests(PoissonArrivals(500.0, seed=6), _mix(), 8,
                              seed=6, uid_base=6_000_000)
        res = run_open_loop(eng, reqs, decode_burst=4, max_live=2)
        rep = res.report
        assert rep["requests"]["completed"] == 8
        # door wait is measured, not hidden: later requests' queue
        # wait >> the first ones'
        qw = rep["latency"]["queue_wait_s"]
        assert qw["count"] == 8 and qw["max"] > qw["min"]


# ------------------------------------------------------------------ #
# engine admission hooks (arrivals= / deadlines=)
# ------------------------------------------------------------------ #


class TestAdmissionHooks:
    def test_arrival_stamp_anchors_slo(self, eng):
        import time
        uid = 7_000_000
        arrived = time.monotonic() - 5.0       # offered 5 s ago
        res = eng.put([uid], [list(range(1, 13))], _greedy=True,
                      arrivals={uid: arrived})
        assert uid in res
        seq = eng.state.sequences[uid]
        assert seq.admitted_at == arrived
        # queue wait measured from the ARRIVAL, so it swallows the
        # driver-side 5 s
        assert seq.first_sched_at - seq.admitted_at > 4.9
        eng.flush(uid)

    def test_per_request_deadline_expires_from_arrival(self, eng):
        import time
        uid = 7_000_001
        res = eng.put([uid], [list(range(1, 13))], _greedy=True,
                      arrivals={uid: time.monotonic() - 5.0},
                      deadlines={uid: 0.5})    # expired 4.5 s ago
        assert uid not in res
        assert eng.rejections[uid]["reason"] == "deadline_exceeded"
        assert eng.state.get(uid) is None      # aborted + flushed

    def test_deadline_dict_overrides_engine_default(self, eng):
        import time
        uid = 7_000_002
        res = eng.put([uid], [list(range(1, 13))], _greedy=True,
                      arrivals={uid: time.monotonic()},
                      deadlines={uid: 60.0})
        assert uid in res
        seq = eng.state.sequences[uid]
        assert seq.deadline_at is not None
        assert seq.deadline_at - time.monotonic() > 50.0
        eng.flush(uid)
