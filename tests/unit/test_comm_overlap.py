"""Decomposed + quantized TP collectives (ISSUE 6): the ring
reduce-scatter / all-gather builders in ``comm/comm.py``.

Covers what the engine-level parity tests cannot isolate: the ring
algebra itself (RS+AG == psum, RS == psum_scatter shard-for-shard), the
EQuARX accuracy claim (per-chunk-scale int8 error on adversarial
outlier-heavy activations is no worse than the legacy monolithic
quantized all-gather), the env-knob resolver, and the watchdog/log_name
plumbing through the new ops.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm.comms_logging import last_collective
from deepspeed_tpu.ops.kernels.quantization import sym_quantize_rowwise
from deepspeed_tpu.utils.jax_compat import shard_map


def _mesh(tp):
    return Mesh(np.asarray(jax.devices()[:tp]), ("model",))


def _partials(tp, S=3, E=16, seed=0, outliers=False):
    """[tp, S, E] f32 per-chip partial sums. ``outliers`` plants a few
    huge columns per row — the adversarial regime where a full-row scale
    collapses and per-chunk scales keep their resolution."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(tp, S, E)).astype(np.float32) * 0.1
    if outliers:
        cols = rng.integers(0, E, size=2)
        x[:, :, cols] += rng.choice([-100.0, 100.0], size=(tp, S, 2))
    return jnp.asarray(x)


def _run_decomposed(full, tp, chunks, quant_bits=None):
    """decomposed_all_reduce over a tp-chip model mesh; returns every
    chip's view [tp, S, E] (they must agree)."""
    def body(x):
        return comm.decomposed_all_reduce(
            x[0], axis_name="model", chunks=chunks,
            quant_bits=quant_bits)[None]

    f = shard_map(body, mesh=_mesh(tp), in_specs=P("model"),
                  out_specs=P("model"), check_vma=False)
    return jax.jit(f)(full)


class TestRingAlgebra:
    def test_tp2_bitwise_psum_parity(self):
        # one commutative fp add — the ring is bit-identical to psum
        full = _partials(2)
        for chunks in (1, 2, 4):
            got = _run_decomposed(full, 2, chunks)
            want = jax.jit(shard_map(
                lambda x: jax.lax.psum(x, "model"), mesh=_mesh(2),
                in_specs=P("model"), out_specs=P("model"),
                check_vma=False))(full)
            assert (np.asarray(got) == np.asarray(want)).all(), \
                f"chunks={chunks}"

    @pytest.mark.parametrize("tp", [2, 4])
    def test_rs_ag_matches_exact_sum(self, tp):
        full = _partials(tp, seed=tp)
        for chunks in (1, 2):
            got = _run_decomposed(full, tp, chunks)
            want = np.broadcast_to(np.asarray(full).sum(0), full.shape)
            np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    def test_ring_reduce_scatter_matches_psum_scatter(self):
        tp = 4
        full = _partials(tp, seed=7)

        def rs_ring(x):
            return comm.ring_reduce_scatter(x[0], axis_name="model")[None]

        def rs_lax(x):
            return jax.lax.psum_scatter(x[0], "model",
                                        scatter_dimension=1, tiled=True)[None]

        kw = dict(mesh=_mesh(tp), in_specs=P("model"),
                  out_specs=P("model"), check_vma=False)
        got = jax.jit(shard_map(rs_ring, **kw))(full)
        want = jax.jit(shard_map(rs_lax, **kw))(full)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    def test_indivisible_last_dim_degrades_safely(self):
        # E=18 at tp=2: chunks=4 cannot tile (18 % 8) -> largest dividing
        # chunking; still exact
        full = _partials(2, E=18, seed=9)
        got = _run_decomposed(full, 2, chunks=4)
        want = np.broadcast_to(np.asarray(full).sum(0), full.shape)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


class TestQuantizedRing:
    def _monolithic_err(self, full):
        """The legacy tp_quantized_comm schedule, emulated exactly: each
        chip quantizes its local partial with ONE per-row scale over the
        full width, gathers, dequant-sums."""
        q, s = sym_quantize_rowwise(full, 8)         # rows = full E width
        deq = (q.astype(jnp.float32) * s)
        return np.abs(np.asarray(deq.sum(0))
                      - np.asarray(full.astype(jnp.float32).sum(0)))

    @pytest.mark.parametrize("tp", [2, 4])
    def test_chunked_scales_beat_monolithic_on_outliers(self, tp):
        # EQuARX claim: per-chunk scales bound the outlier blast radius to
        # one chunk, so the decomposed int8 schedule's error on
        # outlier-heavy activations is <= the monolithic path's (which
        # spends its 8 bits on a 100.0 absmax for every element)
        full = _partials(tp, S=4, E=32, seed=11 + tp, outliers=True)
        got = _run_decomposed(full, tp, chunks=4, quant_bits=8)
        exact = np.asarray(full.astype(jnp.float32).sum(0))
        err_ring = np.abs(np.asarray(got)[0] - exact)
        err_mono = self._monolithic_err(full)
        assert err_ring.mean() <= err_mono.mean(), \
            (err_ring.mean(), err_mono.mean())
        # and it is a real quantized path, not accidentally exact
        assert err_ring.max() > 0

    def test_quantized_ring_close_on_smooth_activations(self):
        full = _partials(2, seed=13)
        got = _run_decomposed(full, 2, chunks=2, quant_bits=8)
        want = np.asarray(full.astype(jnp.float32).sum(0))
        # int8 with ~0.1-magnitude rows: error bounded by a few quant steps
        np.testing.assert_allclose(np.asarray(got)[0], want, atol=2e-2)


class TestKnobsAndPlumbing:
    def test_resolver_defaults_and_env(self, monkeypatch):
        monkeypatch.delenv("DSTPU_TP_OVERLAP", raising=False)
        monkeypatch.delenv("DSTPU_TP_OVERLAP_CHUNKS", raising=False)
        assert comm.resolve_tp_overlap() == ("off", 1)
        assert comm.resolve_tp_overlap("rs_ag", 8) == ("rs_ag", 1)
        assert comm.resolve_tp_overlap("rs_ag_chunked", 4) \
            == ("rs_ag_chunked", 4)
        monkeypatch.setenv("DSTPU_TP_OVERLAP", "rs_ag_chunked:3")
        assert comm.resolve_tp_overlap() == ("rs_ag_chunked", 3)
        monkeypatch.setenv("DSTPU_TP_OVERLAP_CHUNKS", "5")
        assert comm.resolve_tp_overlap() == ("rs_ag_chunked", 5)
        monkeypatch.setenv("DSTPU_TP_OVERLAP", "bogus")
        with pytest.raises(ValueError, match="DSTPU_TP_OVERLAP"):
            comm.resolve_tp_overlap()

    def test_watchdog_names_ring_hops(self):
        # the satellite: log_name rides every decomposed hop, so the
        # resilience watchdog can still name the stalled collective site
        full = _partials(2, seed=17)
        def body(x):
            return comm.decomposed_all_reduce(
                x[0], axis_name="model", chunks=1,
                log_name="tp_all_reduce")[None]
        f = shard_map(body, mesh=_mesh(2), in_specs=P("model"),
                      out_specs=P("model"), check_vma=False)
        jax.jit(f)(full)          # trace records each hop
        rec = last_collective()
        assert rec is not None
        assert rec["log_name"] == "tp_all_reduce"
        # the last traced hop is the all-gather phase of the ring
        assert rec["op"] == "all_gather"
