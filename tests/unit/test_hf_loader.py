"""HF checkpoint interop — logit parity against transformers (torch CPU).

This is the reference's central integration test pattern
(tests/unit/inference/test_inference.py: DS outputs vs vanilla HF pipeline):
save a tiny HF model with transformers, load it through
deepspeed_tpu.checkpoint.hf_loader, and compare logits."""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.checkpoint.hf_loader import (
    convert_hf_state, load_hf_model, load_hf_state_dict, read_safetensors)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _logit_match(ours, theirs, atol=2e-3):
    ours = np.asarray(ours, np.float32)
    theirs = np.asarray(theirs, np.float32)
    err = np.abs(ours - theirs).max()
    scale = np.abs(theirs).max()
    assert err < atol * max(scale, 1.0), f"max err {err} vs scale {scale}"


class TestSafetensorsReader:
    def test_roundtrip(self, tmp_path):
        try:
            import safetensors.torch as st
        except ImportError:
            pytest.skip("safetensors not installed")
        tensors = {"a": torch.randn(3, 4), "b": torch.arange(6).int()}
        st.save_file(tensors, str(tmp_path / "m.safetensors"))
        out = read_safetensors(str(tmp_path / "m.safetensors"))
        np.testing.assert_allclose(out["a"], tensors["a"].numpy())
        np.testing.assert_array_equal(out["b"], tensors["b"].numpy())


class TestLlamaParity:
    def test_logits_match_transformers(self, tmp_path):
        hf_cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            rms_norm_eps=1e-5, tie_word_embeddings=False)
        hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
        hf_model.save_pretrained(tmp_path)

        arch, cfg, params = load_hf_model(str(tmp_path))
        assert arch == "llama"
        cfg = dataclasses.replace(cfg, dtype=jnp.float32,
                                  param_dtype=jnp.float32,
                                  attention_impl="xla")
        from deepspeed_tpu.models.llama import Llama
        model = Llama(cfg)
        tokens = np.random.RandomState(0).randint(0, 128, size=(2, 12))
        ours = model.apply({"params": params},
                           jnp.asarray(tokens, jnp.int32))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(tokens)).logits
        _logit_match(ours, theirs)

    def test_generate_through_hybrid_engine(self, tmp_path, devices8):
        """Full user journey: HF checkpoint -> train step + greedy decode."""
        hf_cfg = transformers.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2,
            num_key_value_heads=2, max_position_embeddings=64,
            tie_word_embeddings=False)
        transformers.LlamaForCausalLM(hf_cfg).save_pretrained(tmp_path)
        arch, cfg, params = load_hf_model(str(tmp_path))
        cfg = dataclasses.replace(cfg, dtype=jnp.float32,
                                  param_dtype=jnp.float32,
                                  attention_impl="xla")
        from deepspeed_tpu.models.llama import Llama, make_model
        import deepspeed_tpu as dstpu
        model, init_fn, loss_fn = make_model(cfg)
        apply_fn = lambda p, t: model.apply({"params": p}, t)  # noqa: E731
        engine, _, _, _ = dstpu.initialize(
            loss_fn=loss_fn, model=apply_fn, params=params, config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "hybrid_engine": {"enabled": True, "max_out_tokens": 4}})
        loss = float(engine.train_batch(
            {"tokens": jnp.ones((16, 13), jnp.int32)}))
        assert np.isfinite(loss)
        ctx, new = engine.generate(jnp.asarray([[1, 2, 3]], jnp.int32),
                                   max_new_tokens=3)
        assert new.shape == (1, 3)


class TestGPT2Parity:
    def test_logits_match_transformers(self, tmp_path):
        hf_cfg = transformers.GPT2Config(
            vocab_size=96, n_positions=64, n_embd=48, n_layer=2, n_head=4)
        hf_model = transformers.GPT2LMHeadModel(hf_cfg).eval()
        hf_model.save_pretrained(tmp_path)
        arch, cfg, params = load_hf_model(str(tmp_path))
        assert arch == "gpt2"
        cfg = dataclasses.replace(cfg, dtype=jnp.float32,
                                  param_dtype=jnp.float32)
        from deepspeed_tpu.models.gpt2 import GPT2
        model = GPT2(cfg)
        tokens = np.random.RandomState(1).randint(0, 96, size=(1, 10))
        ours = model.apply({"params": params},
                           jnp.asarray(tokens, jnp.int32))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(tokens)).logits
        _logit_match(ours, theirs)


class TestOPTParity:
    def test_logits_match_transformers(self, tmp_path):
        hf_cfg = transformers.OPTConfig(
            vocab_size=96, hidden_size=48, ffn_dim=96,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64, do_layer_norm_before=True,
            word_embed_proj_dim=48)
        hf_model = transformers.OPTForCausalLM(hf_cfg).eval()
        hf_model.save_pretrained(tmp_path)
        arch, cfg, params = load_hf_model(str(tmp_path))
        assert arch == "opt"
        cfg = dataclasses.replace(cfg, dtype=jnp.float32,
                                  param_dtype=jnp.float32)
        from deepspeed_tpu.models.opt import OPT
        model = OPT(cfg)
        tokens = np.random.RandomState(2).randint(0, 96, size=(1, 9))
        ours = model.apply({"params": params},
                           jnp.asarray(tokens, jnp.int32))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(tokens)).logits
        _logit_match(ours, theirs)


class TestConvertErrors:
    def test_unmapped_strict_raises(self):
        with pytest.raises(ValueError):
            convert_hf_state("llama", {"bogus.weight": np.zeros((2, 2))})

    def test_unknown_arch(self):
        with pytest.raises(ValueError):
            convert_hf_state("notanarch", {})


class TestBuildHfEngine:
    def test_llama_end_to_end(self, tmp_path):
        """build_hf_engine parity: HF dir -> ragged engine -> greedy decode
        matches the plain full-forward reference."""
        from deepspeed_tpu.inference.v2.engine_factory import build_hf_engine
        from deepspeed_tpu.inference.v2.config import RaggedInferenceConfig
        hf_cfg = transformers.LlamaConfig(
            vocab_size=96, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2,
            num_key_value_heads=2, max_position_embeddings=64,
            tie_word_embeddings=False)
        hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
        hf_model.save_pretrained(tmp_path)

        eng = build_hf_engine(str(tmp_path), dtype="float32",
                              engine_config=RaggedInferenceConfig(
                                  max_seqs=2, chunk_size=8, block_size=4,
                                  num_blocks=64, max_blocks_per_seq=16,
                                  dtype="float32"))
        prompt = list(np.random.RandomState(0).randint(1, 90, 9))
        gen = eng.generate([prompt], max_new_tokens=4)[0]
        # reference: greedy decode with transformers
        import torch as _t
        toks = list(prompt)
        for _ in range(4):
            with _t.no_grad():
                logits = hf_model(_t.tensor([toks])).logits
            toks.append(int(logits[0, -1].argmax()))
        assert gen == toks[len(prompt):]

    def test_quantized_engine_runs(self, tmp_path):
        from deepspeed_tpu.inference.v2.engine_factory import build_hf_engine
        from deepspeed_tpu.inference.v2.config import RaggedInferenceConfig
        hf_cfg = transformers.LlamaConfig(
            vocab_size=96, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2,
            num_key_value_heads=2, max_position_embeddings=64,
            tie_word_embeddings=False)
        transformers.LlamaForCausalLM(hf_cfg).save_pretrained(tmp_path)
        eng = build_hf_engine(str(tmp_path), dtype="float32",
                              quantization_mode="wf8",
                              engine_config=RaggedInferenceConfig(
                                  max_seqs=2, chunk_size=8, block_size=4,
                                  num_blocks=64, max_blocks_per_seq=16,
                                  dtype="float32"))
        out = eng.generate([[1, 2, 3, 4, 5]], max_new_tokens=3)[0]
        assert len(out) == 3

    def test_unknown_arch_raises(self, tmp_path):
        from deepspeed_tpu.inference.v2.engine_factory import build_hf_engine
        hf_cfg = transformers.BertConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=1,
            num_attention_heads=2, intermediate_size=64)
        transformers.BertModel(hf_cfg).save_pretrained(tmp_path)
        with pytest.raises(ValueError):
            build_hf_engine(str(tmp_path))


class TestPhi3Parity:
    def test_fused_tensors_split_and_logits_match(self, tmp_path):
        hf_cfg = transformers.Phi3Config(
            vocab_size=96, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            pad_token_id=0, tie_word_embeddings=False)
        hf_model = transformers.Phi3ForCausalLM(hf_cfg).eval()
        hf_model.save_pretrained(tmp_path)
        arch, cfg, params = load_hf_model(str(tmp_path))
        assert arch == "phi3"
        cfg = dataclasses.replace(cfg, dtype=jnp.float32,
                                  param_dtype=jnp.float32,
                                  attention_impl="xla", qkv_bias=False)
        from deepspeed_tpu.models.llama import Llama
        model = Llama(cfg)
        tokens = np.random.RandomState(3).randint(0, 96, size=(1, 10))
        ours = model.apply({"params": params},
                           jnp.asarray(tokens, jnp.int32))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(tokens)).logits
        _logit_match(ours, theirs)


class TestQwen2MoeRaggedRunner:
    def test_shared_expert_in_ragged_decode(self):
        """In-framework qwen2-moe params: ragged decode matches the full
        forward (shared expert included)."""
        from deepspeed_tpu.inference.v2 import (
            InferenceEngineV2, RaggedInferenceConfig)
        from deepspeed_tpu.models.mixtral import Mixtral, MixtralConfig
        cfg = dataclasses.replace(
            MixtralConfig.tiny(num_experts=2, shared_expert_size=24),
            dtype=jnp.float32, param_dtype=jnp.float32,
            attention_impl="xla", drop_tokens=False)
        model = Mixtral(cfg)
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "gating": jax.random.PRNGKey(0)},
            jnp.zeros((1, 8), jnp.int32))["params"]
        eng = InferenceEngineV2(cfg, params, RaggedInferenceConfig(
            max_seqs=2, chunk_size=8, block_size=4, num_blocks=64,
            max_blocks_per_seq=16, dtype="float32"))
        prompt = list(np.random.RandomState(0).randint(1, 500, 9))
        gen = eng.generate([prompt], max_new_tokens=4)[0]
        toks = list(prompt)
        for _ in range(4):
            logits = model.apply({"params": params},
                                 jnp.asarray([toks], jnp.int32),
                                 train=False, rngs={"gating": jax.random.PRNGKey(0)})
            toks.append(int(jnp.argmax(logits[0, -1])))
        assert gen == toks[len(prompt):]


class TestPhiParity:
    def test_logits_and_serving(self, tmp_path):
        hf_cfg = transformers.PhiConfig(
            vocab_size=96, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64, partial_rotary_factor=0.5)
        hf_model = transformers.PhiForCausalLM(hf_cfg).eval()
        hf_model.save_pretrained(tmp_path)
        arch, cfg, params = load_hf_model(str(tmp_path))
        assert arch == "phi"
        cfg = dataclasses.replace(cfg, dtype=jnp.float32,
                                  param_dtype=jnp.float32)
        from deepspeed_tpu.models.phi import Phi
        model = Phi(cfg)
        tokens = np.random.RandomState(4).randint(0, 96, size=(1, 10))
        ours = model.apply({"params": params},
                           jnp.asarray(tokens, jnp.int32))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(tokens)).logits
        _logit_match(ours, theirs)

        # one-call serving from the checkpoint dir
        from deepspeed_tpu.inference.v2.engine_factory import build_hf_engine
        from deepspeed_tpu.inference.v2.config import RaggedInferenceConfig
        eng = build_hf_engine(str(tmp_path), dtype="float32",
                              engine_config=RaggedInferenceConfig(
                                  max_seqs=2, chunk_size=8, block_size=4,
                                  num_blocks=64, max_blocks_per_seq=16,
                                  dtype="float32"))
        prompt = list(np.random.RandomState(5).randint(1, 90, 7))
        gen = eng.generate([prompt], max_new_tokens=3)[0]
        toks = list(prompt)
        for _ in range(3):
            with torch.no_grad():
                logits = hf_model(torch.tensor([toks])).logits
            toks.append(int(logits[0, -1].argmax()))
        assert gen == toks[len(prompt):]


class TestMoEParity:
    def _serve(self, tmp_path, hf_model):
        from deepspeed_tpu.inference.v2.engine_factory import build_hf_engine
        from deepspeed_tpu.inference.v2.config import RaggedInferenceConfig
        hf_model.save_pretrained(tmp_path)
        eng = build_hf_engine(str(tmp_path), dtype="float32",
                              engine_config=RaggedInferenceConfig(
                                  max_seqs=2, chunk_size=8, block_size=4,
                                  num_blocks=64, max_blocks_per_seq=16,
                                  dtype="float32"))
        prompt = list(np.random.RandomState(8).randint(1, 90, 8))
        gen = eng.generate([prompt], max_new_tokens=4)[0]
        toks = list(prompt)
        for _ in range(4):
            with torch.no_grad():
                logits = hf_model(torch.tensor([toks])).logits
            toks.append(int(logits[0, -1].argmax()))
        return gen, toks[len(prompt):]

    def test_mixtral_serving_matches_transformers(self, tmp_path):
        hf_cfg = transformers.MixtralConfig(
            vocab_size=96, hidden_size=32, intermediate_size=48,
            num_hidden_layers=2, num_attention_heads=2,
            num_key_value_heads=2, num_local_experts=4,
            num_experts_per_tok=2, max_position_embeddings=64,
            tie_word_embeddings=False)
        hf_model = transformers.MixtralForCausalLM(hf_cfg).eval()
        gen, ref = self._serve(tmp_path, hf_model)
        assert gen == ref

    def test_qwen2_moe_serving_matches_transformers(self, tmp_path):
        hf_cfg = transformers.Qwen2MoeConfig(
            vocab_size=96, hidden_size=32, intermediate_size=48,
            moe_intermediate_size=24, shared_expert_intermediate_size=40,
            num_hidden_layers=2, num_attention_heads=2,
            num_key_value_heads=2, num_experts=4, num_experts_per_tok=2,
            max_position_embeddings=64, tie_word_embeddings=False,
            decoder_sparse_step=1)
        hf_model = transformers.Qwen2MoeForCausalLM(hf_cfg).eval()
        gen, ref = self._serve(tmp_path, hf_model)
        assert gen == ref

    def test_qwen2_moe_norm_topk_variants(self, tmp_path):
        """Both router normalization modes must match transformers (the HF
        default is norm_topk_prob=False — softmax over all experts, no
        renormalization)."""
        for norm in (False, True):
            d = tmp_path / f"norm_{norm}"
            hf_cfg = transformers.Qwen2MoeConfig(
                vocab_size=96, hidden_size=32, intermediate_size=48,
                moe_intermediate_size=24,
                shared_expert_intermediate_size=40,
                num_hidden_layers=2, num_attention_heads=2,
                num_key_value_heads=2, num_experts=4,
                num_experts_per_tok=2, max_position_embeddings=64,
                tie_word_embeddings=False, decoder_sparse_step=1,
                norm_topk_prob=norm)
            hf_model = transformers.Qwen2MoeForCausalLM(hf_cfg).eval()
            gen, ref = self._serve(d, hf_model)
            assert gen == ref, f"norm_topk_prob={norm}"


class TestQwenV1:
    """Qwen v1 (original model_type "qwen": fused c_attn, w1/w2/c_proj
    SwiGLU, its own config key names — reference
    inference/v2/model_implementations/qwen/). Not in transformers
    (trust_remote_code upstream), so the checkpoint is built from a known
    Llama param tree and parity is checked against our own forward."""

    def test_qwen_checkpoint_serves(self, tmp_path):
        import json

        import torch as _t

        from deepspeed_tpu.inference.v2.config import RaggedInferenceConfig
        from deepspeed_tpu.inference.v2.engine_factory import build_hf_engine
        from deepspeed_tpu.models.llama import Llama, LlamaConfig

        V, H, L, NH, I, T = 96, 32, 2, 2, 48, 64
        cfg = LlamaConfig(vocab_size=V, max_seq_len=T, num_layers=L,
                          num_heads=NH, num_kv_heads=NH, hidden_size=H,
                          intermediate_size=I, qkv_bias=True,
                          rms_eps=1e-6, dtype=jnp.float32,
                          param_dtype=jnp.float32, attention_impl="xla")
        model = Llama(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]

        # re-fuse our params into the qwen v1 on-disk layout
        sd = {}
        sd["transformer.wte.weight"] = np.asarray(params["embed"]["embedding"])
        sd["transformer.ln_f.weight"] = np.asarray(
            params["final_norm"]["scale"])
        sd["lm_head.weight"] = np.asarray(params["lm_head"]["kernel"]).T
        for i in range(L):
            p = params[f"layer_{i}"]
            pre = f"transformer.h.{i}"
            sd[f"{pre}.ln_1.weight"] = np.asarray(p["input_norm"]["scale"])
            sd[f"{pre}.ln_2.weight"] = np.asarray(p["post_attn_norm"]["scale"])
            qkv_w = np.concatenate(
                [np.asarray(p["attn"][f"{x}_proj"]["kernel"]).T
                 for x in "qkv"])
            qkv_b = np.concatenate(
                [np.asarray(p["attn"][f"{x}_proj"]["bias"]) for x in "qkv"])
            sd[f"{pre}.attn.c_attn.weight"] = qkv_w
            sd[f"{pre}.attn.c_attn.bias"] = qkv_b
            sd[f"{pre}.attn.c_proj.weight"] = np.asarray(
                p["attn"]["o_proj"]["kernel"]).T
            sd[f"{pre}.mlp.w2.weight"] = np.asarray(
                p["mlp"]["gate_proj"]["kernel"]).T
            sd[f"{pre}.mlp.w1.weight"] = np.asarray(
                p["mlp"]["up_proj"]["kernel"]).T
            sd[f"{pre}.mlp.c_proj.weight"] = np.asarray(
                p["mlp"]["down_proj"]["kernel"]).T

        with open(tmp_path / "config.json", "w") as f:
            json.dump({"model_type": "qwen", "vocab_size": V,
                       "hidden_size": H, "num_hidden_layers": L,
                       "num_attention_heads": NH,
                       "intermediate_size": 2 * I, "seq_length": T,
                       "rotary_emb_base": 10000.0,
                       "layer_norm_epsilon": 1e-6}, f)
        _t.save({k: _t.from_numpy(v.copy()) for k, v in sd.items()},
                tmp_path / "pytorch_model.bin")

        eng = build_hf_engine(str(tmp_path), dtype="float32",
                              engine_config=RaggedInferenceConfig(
                                  max_seqs=2, chunk_size=8, block_size=4,
                                  num_blocks=64, max_blocks_per_seq=16,
                                  dtype="float32",
                                  attention_impl="paged_flash"))
        prompt = list(np.random.RandomState(0).randint(1, 90, 9))
        gen = eng.generate([prompt], max_new_tokens=4)[0]

        toks = list(prompt)
        for _ in range(4):
            logits = model.apply({"params": params},
                                 jnp.asarray([toks], jnp.int32))
            toks.append(int(jnp.argmax(logits[0, -1])))
        assert gen == toks[len(prompt):]


class TestBloomNeoXGPTJ:
    """BLOOM / GPT-NeoX / GPT-J families end-to-end: HF checkpoint dir ->
    ragged engine -> greedy decode matches transformers (the v1-injection
    breadth rows module_inject/containers/{bloom,gptneox,gptj}.py)."""

    def _serve(self, tmp_path, hf_model, n=4):
        from deepspeed_tpu.inference.v2.config import RaggedInferenceConfig
        from deepspeed_tpu.inference.v2.engine_factory import build_hf_engine
        hf_model.save_pretrained(tmp_path)
        eng = build_hf_engine(str(tmp_path), dtype="float32",
                              engine_config=RaggedInferenceConfig(
                                  max_seqs=2, chunk_size=8, block_size=4,
                                  num_blocks=64, max_blocks_per_seq=16,
                                  dtype="float32",
                                  attention_impl="paged_flash"))
        prompt = list(np.random.RandomState(8).randint(1, 90, 8))
        gen = eng.generate([prompt], max_new_tokens=n)[0]
        toks = list(prompt)
        for _ in range(n):
            with torch.no_grad():
                logits = hf_model(torch.tensor([toks])).logits
            toks.append(int(logits[0, -1].argmax()))
        return gen, toks[len(prompt):]

    def test_bloom_serving_matches_transformers(self, tmp_path):
        hf_cfg = transformers.BloomConfig(
            vocab_size=96, hidden_size=32, n_layer=2, n_head=4,
            tie_word_embeddings=True)
        hf_model = transformers.BloomForCausalLM(hf_cfg).eval()
        gen, ref = self._serve(tmp_path, hf_model)
        assert gen == ref

    @pytest.mark.parametrize("parallel", [True, False])
    def test_gpt_neox_serving_matches_transformers(self, tmp_path, parallel):
        hf_cfg = transformers.GPTNeoXConfig(
            vocab_size=96, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64, rotary_pct=0.25,
            use_parallel_residual=parallel, tie_word_embeddings=False)
        hf_model = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
        gen, ref = self._serve(tmp_path, hf_model)
        assert gen == ref

    def test_gptj_serving_matches_transformers(self, tmp_path):
        hf_cfg = transformers.GPTJConfig(
            vocab_size=96, n_embd=32, n_layer=2, n_head=4, n_positions=64,
            rotary_dim=8, tie_word_embeddings=False)
        hf_model = transformers.GPTJForCausalLM(hf_cfg).eval()
        gen, ref = self._serve(tmp_path, hf_model)
        assert gen == ref

    def test_bloom_training_model_logits_match(self, tmp_path):
        """The TRAINING-side flax Bloom matches transformers too (one fwd)."""
        from deepspeed_tpu.checkpoint.hf_loader import load_hf_model
        from deepspeed_tpu.models.bloom import Bloom
        import dataclasses
        hf_cfg = transformers.BloomConfig(
            vocab_size=96, hidden_size=32, n_layer=2, n_head=4,
            tie_word_embeddings=True)
        hf_model = transformers.BloomForCausalLM(hf_cfg).eval()
        hf_model.save_pretrained(tmp_path)
        arch, cfg, params = load_hf_model(str(tmp_path))
        assert arch == "bloom"
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
        toks = np.random.RandomState(3).randint(1, 90, (1, 12))
        ours = Bloom(cfg).apply({"params": params},
                                jnp.asarray(toks, jnp.int32))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(toks)).logits.numpy()
        _logit_match(np.asarray(ours), theirs)


class TestGPTNeoParity:
    def test_logits_match_transformers(self, tmp_path):
        hf_cfg = transformers.GPTNeoConfig(
            vocab_size=96, max_position_embeddings=64, hidden_size=48,
            num_layers=2, num_heads=4, intermediate_size=96,
            attention_types=[[["global", "local"], 1]], window_size=8)
        hf_model = transformers.GPTNeoForCausalLM(hf_cfg).eval()
        hf_model.save_pretrained(tmp_path)
        arch, cfg, params = load_hf_model(str(tmp_path))
        assert arch == "gpt_neo"
        assert cfg.layer_kinds() == ["global", "local"]
        from deepspeed_tpu.models.gpt_neo import GPTNeo
        model = GPTNeo(cfg)
        # length > window so the local layer's mask actually bites
        tokens = np.random.RandomState(3).randint(0, 96, size=(1, 12))
        ours = model.apply({"params": params},
                           jnp.asarray(tokens, jnp.int32))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(tokens)).logits
        _logit_match(ours, theirs)


class TestDistilBertParity:
    def test_mlm_logits_match_transformers(self, tmp_path):
        hf_cfg = transformers.DistilBertConfig(
            vocab_size=96, max_position_embeddings=64, dim=48, n_layers=2,
            n_heads=4, hidden_dim=96)
        hf_model = transformers.DistilBertForMaskedLM(hf_cfg).eval()
        hf_model.save_pretrained(tmp_path)
        arch, cfg, params = load_hf_model(str(tmp_path))
        assert arch == "distilbert"
        assert cfg.type_vocab_size == 0
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
        from deepspeed_tpu.models.bert import Bert
        model = Bert(cfg)
        tokens = np.random.RandomState(4).randint(0, 96, size=(1, 11))
        ours = model.apply({"params": params},
                           jnp.asarray(tokens, jnp.int32))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(tokens)).logits
        _logit_match(ours, theirs)
