"""Step-time attribution + fleet request tracing tests (ISSUE 14).

The layer's contract: the five step-wall components SUM to an
externally measured decode window's wall clock (tolerance-gated — the
closure IS the host-gap definition), attribution on/off changes no
token, a synthetic host-side stall inside the serve loop is LOCALIZED
to the host-gap component, one request's trace context follows it
through router scoring → replica execution → SIGTERM drain → survivor
replay as ONE gapless ordered track in the merged fleet Chrome trace,
same-numbered uids from different replicas no longer collide after a
multi-file merge (the tid-namespacing regression), and the
``bench_compare`` regression sentinel exits non-zero on planted
regressions / missing phases and zero on improvements.
"""

import json
import os
import signal
import sys
import time

import numpy as np
import pytest

from deepspeed_tpu.telemetry.attribution import (ATTRIBUTION_COMPONENTS,
                                                 STEP_WALL_COMPONENTS,
                                                 attribution_report,
                                                 comm_share,
                                                 component_totals)
from deepspeed_tpu.telemetry.flight_recorder import (FlightRecorder,
                                                     merge_chrome_traces,
                                                     request_tracks)

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_compare  # noqa: E402


def _gpt2():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config
    mcfg = GPT2Config(vocab_size=96, max_seq_len=256, num_layers=2,
                      num_heads=2, hidden_size=32, dtype=jnp.float32)
    params = GPT2(mcfg).init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 8), jnp.int32))["params"]
    return mcfg, params


_MODEL = None


def _engine(**kw):
    global _MODEL
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceConfig)
    if _MODEL is None:
        _MODEL = _gpt2()
    mcfg, params = _MODEL
    base = dict(max_seqs=4, chunk_size=8, block_size=8, num_blocks=64,
                max_blocks_per_seq=16, dtype="float32",
                attention_impl="dense", decode_loop_steps=0,
                serve_pipeline_depth=2, prefix_cache=False)
    base.update(kw)
    return InferenceEngineV2(mcfg, params, RaggedInferenceConfig(**base))


def _prompts(n=3, ln=12, seed=7):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 96, ln).tolist() for _ in range(n)]


def _serve_window(eng, uids, last, gen):
    """One timed pipelined decode window: (wall_s, outs)."""
    t0 = time.perf_counter()
    outs = eng.decode_pipelined(uids, last, gen)
    return time.perf_counter() - t0, outs


# ------------------------------------------------------------------ #
# step-time attribution
# ------------------------------------------------------------------ #


class TestStepAttribution:
    def test_components_sum_to_measured_wall(self):
        eng = _engine()
        uids = [0, 1, 2]
        prompts = _prompts()
        first = eng.put(uids, prompts, _greedy=True)
        warm = eng.decode_pipelined(uids, [first[u] for u in uids], 2)
        snap0 = eng.metrics.snapshot()
        wall, outs = _serve_window(eng, uids,
                                   [warm[u][-1] for u in uids], 16)
        snap1 = eng.metrics.snapshot()
        comps = component_totals(snap1, snap0)
        comp_sum = sum(comps[c] for c in STEP_WALL_COMPONENTS)
        # tolerance owns the engine-call overhead outside the serve
        # loop (staging the decode feed, ring setup) — generous on a
        # shared CPU box, but the sum must clearly track the wall
        assert abs(wall - comp_sum) / wall < 0.35, (wall, comps)
        assert all(comps[c] >= 0.0 for c in comps)
        # every bracketed component actually recorded something
        for c in ("plan", "dispatch", "device_execute", "commit_apply",
                  "host_gap"):
            assert comps[c] > 0.0, (c, comps)
        rep = attribution_report(snap1, snap0)
        assert rep["dominant"] in STEP_WALL_COMPONENTS
        assert rep["closure_err_frac"] is not None
        # internal closure (vs the observer's own step-wall histogram)
        # is tight by construction
        assert rep["closure_err_frac"] < 0.01

    def test_attrib_off_token_parity_and_no_attrib_hists(self):
        uids = [0, 1, 2]
        prompts = _prompts(seed=11)
        eng_on = _engine()
        f_on = eng_on.put(uids, prompts, _greedy=True)
        o_on = eng_on.decode_pipelined(uids, [f_on[u] for u in uids], 12)
        os.environ["DSTPU_ATTRIB"] = "0"
        try:
            eng_off = _engine()
            f_off = eng_off.put(uids, prompts, _greedy=True)
            o_off = eng_off.decode_pipelined(uids,
                                             [f_off[u] for u in uids],
                                             12)
        finally:
            os.environ.pop("DSTPU_ATTRIB", None)
        assert f_on == f_off and o_on == o_off
        # the off engine never feeds the attribution histograms
        snap = eng_off.metrics.snapshot()
        assert snap["histograms"].get(
            "serve_host_gap_s", {}).get("count", 0) == 0
        assert snap["histograms"].get(
            "serve_step_wall_s", {}).get("count", 0) == 0
        # the on engine does
        snap_on = eng_on.metrics.snapshot()
        assert snap_on["histograms"]["serve_step_wall_s"]["count"] > 0

    def test_injected_host_gap_localized(self):
        eng = _engine()
        uids = [0, 1, 2]
        first = eng.put(uids, _prompts(seed=3), _greedy=True)
        warm = eng.decode_pipelined(uids, [first[u] for u in uids], 2)
        last = [warm[u][-1] for u in uids]
        snap0 = eng.metrics.snapshot()
        _, outs = _serve_window(eng, uids, last, 12)
        snap1 = eng.metrics.snapshot()
        base = component_totals(snap1, snap0)
        # inject a 1 ms stall per pipeline fill into the UNBRACKETED
        # region of the loop (the stand-in for resume scans / GC)
        orig = eng._try_resume

        def slow():
            time.sleep(0.001)
            orig()

        eng._try_resume = slow
        try:
            _, outs2 = _serve_window(eng, uids,
                                     [outs[u][-1] for u in uids], 12)
        finally:
            eng._try_resume = orig
        inj = component_totals(eng.metrics.snapshot(), snap1)
        deltas = {c: inj[c] - base[c] for c in STEP_WALL_COMPONENTS}
        assert max(deltas, key=deltas.get) == "host_gap", deltas
        # at least ~12 fills x 1 ms must have landed in host_gap
        assert deltas["host_gap"] > 0.008, deltas

    def test_attrib_counters_delta_synced(self):
        eng = _engine()
        uids = [0, 1]
        first = eng.put(uids, _prompts(2, seed=5), _greedy=True)
        eng.decode_pipelined(uids, [first[u] for u in uids], 6)
        eng._obs.sync_gauges()
        snap = eng.metrics.snapshot()
        comps = component_totals(snap)
        for comp, _hist in ATTRIBUTION_COMPONENTS:
            if comps[comp] <= 0.0:
                continue
            key = f'serve_attrib_seconds_total{{component="{comp}"}}'
            assert snap["counters"].get(key) == pytest.approx(
                comps[comp], rel=1e-6), key

    def test_comm_share_tp1(self):
        eng = _engine()
        share = comm_share(eng)
        assert share is not None
        assert share["collectives_per_step"] == 0
        assert share["comm_op_share"] == 0.0
        assert share["dot_generals_per_step"] > 0
        assert share["host_callbacks"] == 0

    def test_audited_programs_clean_with_attrib_on(self):
        from deepspeed_tpu.analysis.program_audit import \
            audit_serve_programs
        eng = _engine()
        uids = [0]
        first = eng.put(uids, _prompts(1, seed=9), _greedy=True)
        eng.decode_pipelined(uids, [first[0]], 4)
        reports = audit_serve_programs(
            eng, programs=("step_greedy", "step_greedy_fb"))
        assert sum(r.host_callbacks for r in reports.values()) == 0


# ------------------------------------------------------------------ #
# trace merge — tid namespacing + trace stitching
# ------------------------------------------------------------------ #


class TestTraceMerge:
    def _dump(self, spans, wall_base=1000.0):
        """A synthetic flight dump in the recorder's export shape."""
        rec = FlightRecorder(capacity=64)
        for name, t0, t1, args in spans:
            rec.record(name, t0, t1, args=args)
        d = rec.to_chrome_trace()
        d["otherData"]["wall_time_base"] = wall_base
        return d

    def test_same_uid_different_replicas_do_not_collide(self):
        # the regression: tid = uid + 1 per replica folded DIFFERENT
        # requests with the same uid number onto one merged track
        a = self._dump([("req_admit", 0.0, 0.0, {"uid": 7}),
                        ("req_finish", 0.1, 0.1, {"uid": 7})])
        b = self._dump([("req_admit", 0.0, 0.0, {"uid": 7}),
                        ("req_finish", 0.2, 0.2, {"uid": 7})])
        merged = merge_chrome_traces([a, b], ["r0", "r1"])
        tracks = request_tracks(merged)
        assert set(tracks) == {"req r0/uid7", "req r1/uid7"}
        tids = {ev["tid"] for evs in tracks.values() for ev in evs}
        assert len(tids) == 2

    def test_trace_context_stitches_across_sources(self):
        a = self._dump([("req_admit", 0.0, 0.0,
                         {"uid": 7, "trace": "p/7#1"})])
        b = self._dump([("req_finish", 0.0, 0.0,
                         {"uid": 7, "trace": "p/7#1"})],
                       wall_base=1000.5)
        merged = merge_chrome_traces([a, b], ["r0", "r1"])
        tracks = request_tracks(merged)
        assert set(tracks) == {"req p/7#1"}
        evs = tracks["req p/7#1"]
        assert [e["name"] for e in evs] == ["req_admit", "req_finish"]
        # clock rebase: r1's dump starts 0.5 s of wall later
        assert evs[1]["ts"] - evs[0]["ts"] == pytest.approx(5e5, rel=0.01)
        assert {e["args"]["source"] for e in evs} == {"r0", "r1"}

    def test_engine_lanes_keep_per_source_tracks(self):
        a = self._dump([("plan", 0.0, 0.01, None)])
        b = self._dump([("plan", 0.0, 0.01, None)])
        merged = merge_chrome_traces([a, b], ["r0", "r1"])
        lanes = {ev["tid"] for ev in merged["traceEvents"]
                 if ev.get("ph") != "M"}
        assert lanes == {0, 1}

    def test_short_sources_refused(self):
        with pytest.raises(ValueError):
            merge_chrome_traces([self._dump([])], [])


# ------------------------------------------------------------------ #
# fleet: one request's track through a SIGTERM drain/replay
# ------------------------------------------------------------------ #


class TestFleetTraceReconstruction:
    def test_sigterm_drain_replay_gapless_track(self):
        from deepspeed_tpu.resilience.preemption import PreemptionHandler
        from deepspeed_tpu.serving import ReplicaPool
        pool = ReplicaPool([_engine(), _engine()], policy="round_robin")
        uids = list(range(4))
        prompts = {u: p for u, p in zip(uids, _prompts(4, seed=13))}
        out = pool.put(uids, [prompts[u] for u in uids], _greedy=True)
        toks = {u: [int(out[u])] for u in uids}
        r1 = pool.decode_pipelined(uids, [toks[u][-1] for u in uids], 3)
        for u in uids:
            toks[u].extend(r1[u])
        victim = pool.owner_of(0)
        assert victim is not None
        handler = PreemptionHandler()
        try:
            victim.engine.attach_preemption(handler)
            os.kill(os.getpid(), signal.SIGTERM)
            assert handler.wait(2.0) and handler.preempted
            # next pool entry absorbs: drain -> survivor replay; the
            # caller's stream stays gapless through the membership
            # change and the trace context rides the manifest
            r2 = pool.decode_pipelined(uids,
                                       [toks[u][-1] for u in uids], 3)
            for u in uids:
                toks[u].extend(r2[u])
        finally:
            handler.uninstall()
        assert all(len(toks[u]) == 7 for u in uids)
        for u in uids:
            pool.flush(u)
        path = pool.dump_merged_trace("/tmp/dstpu_test_fleet_trace.json")
        with open(path, encoding="utf-8") as f:
            merged = json.load(f)
        tracks = request_tracks(merged)
        # every request has exactly ONE track, keyed by its trace id —
        # NO orphan (source, uid)-keyed tracks left behind for the
        # drained sequences
        assert len(tracks) == 4
        assert not any("/uid" in name for name in tracks), tracks.keys()
        moved = [t for t in tracks.values()
                 if len({e["args"]["source"] for e in t
                         if e["args"].get("source", "").startswith("r")}
                        ) > 1]
        # the victim owned >= 1 request; its track must span BOTH
        # replicas (pre-drain spans + survivor replay spans)
        assert moved, {k: sorted({e['args'].get('source')
                                  for e in v}) for k, v in tracks.items()}
        for evs in tracks.values():
            names = [e["name"] for e in evs]
            # ordered end-to-end: the route decision opens the track,
            # the terminal finish closes it
            assert names[0] == "req_route"
            assert names[-1] == "req_finish"
            # gapless across the membership change: the drain-side
            # finish (outcome=drained), the traced re-route decision
            # and the survivor's spans sit in wall-clock order
            finishes = [e for e in evs if e["name"] == "req_finish"]
            if len(finishes) > 1:
                assert finishes[0]["args"]["outcome"] == "drained"
                assert finishes[-1]["args"]["outcome"] == "completed"
                reroutes = [e for e in evs if e["name"] == "req_route"
                            and e["args"].get("replay")]
                assert reroutes, names
                assert finishes[0]["ts"] <= reroutes[0]["ts"] \
                    <= finishes[-1]["ts"]
                assert any(e["args"].get("scores") is not None
                           or e["args"].get("policy") for e in reroutes)

    def test_router_decision_span_carries_scores(self):
        from deepspeed_tpu.serving import ReplicaPool
        pool = ReplicaPool([_engine(prefix_cache=True),
                            _engine(prefix_cache=True)],
                           policy="prefix_aware")
        out = pool.put([0], [_prompts(1, seed=17)[0]], _greedy=True)
        assert 0 in out
        routes = [s for s in pool.flight.spans if s[0] == "req_route"]
        assert len(routes) == 1
        args = routes[0][4]
        assert args["policy"] == "prefix_aware"
        assert set(args["scores"]) == {"r0", "r1"}
        assert args["chosen"] in ("r0", "r1")
        assert args["trace"].startswith("fleet/0#")
        pool.flush(0)


# ------------------------------------------------------------------ #
# bench_compare golden diffs
# ------------------------------------------------------------------ #


class TestBenchCompare:
    OLD = {"metric": "x", "value": 10.0, "detail": {
        "serve": {"decode_tokens_per_sec": 100.0, "token_parity": True,
                  "fresh_compiles_measured": 0},
        "serve_obs": {"overhead_frac": 0.01},
        "serve_attrib": {"closure_err_frac": 0.01,
                         "decode_steps_per_sec": 50.0}}}

    def test_improvement_passes(self):
        new = {"metric": "x", "value": 11.0, "detail": {
            "serve": {"decode_tokens_per_sec": 130.0,
                      "token_parity": True,
                      "fresh_compiles_measured": 0},
            "serve_obs": {"overhead_frac": 0.005},
            "serve_attrib": {"closure_err_frac": 0.008,
                             "decode_steps_per_sec": 60.0}}}
        res = bench_compare.compare_rounds(self.OLD, new)
        assert res["ok"] and not res["regressions"]
        assert any(r["metric"] == "serve.decode_tokens_per_sec"
                   for r in res["improvements"])

    def test_planted_regression_fails(self):
        new = {"metric": "x", "value": 9.9, "detail": {
            "serve": {"decode_tokens_per_sec": 60.0,
                      "token_parity": False,
                      "fresh_compiles_measured": 1},
            "serve_obs": {"overhead_frac": 0.01},
            "serve_attrib": {"closure_err_frac": 0.01,
                             "decode_steps_per_sec": 50.0}}}
        res = bench_compare.compare_rounds(self.OLD, new)
        assert not res["ok"]
        metrics = {r["metric"] for r in res["regressions"]}
        assert "serve.decode_tokens_per_sec" in metrics
        assert "serve.token_parity" in metrics        # gate flip
        assert "serve.fresh_compiles_measured" in metrics   # 0-band
        # within-band drift never gates
        assert "serve_attrib.decode_steps_per_sec" not in metrics

    def test_missing_phase_fails_unless_allowed(self):
        new = {"metric": "x", "value": 10.2, "detail": {
            "serve": {"decode_tokens_per_sec": 101.0,
                      "token_parity": True,
                      "fresh_compiles_measured": 0},
            "serve_obs": {"overhead_frac": 0.01}}}
        res = bench_compare.compare_rounds(self.OLD, new)
        assert not res["ok"]
        assert res["missing_phases"] == ["serve_attrib"]
        res2 = bench_compare.compare_rounds(self.OLD, new,
                                            allow_missing=True)
        assert res2["ok"]

    def test_cli_exit_codes_and_wrapper_shape(self, tmp_path):
        old_p = tmp_path / "old.json"
        new_p = tmp_path / "new.json"
        old_p.write_text(json.dumps(self.OLD))
        # the driver-wrapper shape: bench row embedded in stdout tail
        bad = dict(self.OLD)
        bad = json.loads(json.dumps(self.OLD))
        bad["detail"]["serve"]["decode_tokens_per_sec"] = 10.0
        new_p.write_text(json.dumps(
            {"n": 17, "rc": 0,
             "tail": "noise\n" + json.dumps(bad) + "\n"}))
        assert bench_compare.main([str(old_p), str(new_p)]) == 1
        good = json.loads(json.dumps(self.OLD))
        new_p.write_text(json.dumps(good))
        assert bench_compare.main([str(old_p), str(new_p)]) == 0
        assert bench_compare.main([str(old_p), "/nonexistent.json"]) == 2
