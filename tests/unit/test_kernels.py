"""Pallas kernel parity tests (interpret mode on the CPU mesh) — the analogue
of the reference's per-op numerical tests under ``tests/unit/ops/``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.kernels import (
    dequantize_blockwise,
    flash_attention,
    fused_adamw_update,
    fused_layer_norm,
    fused_rms_norm,
    quant_dequant,
    quantize_blockwise,
)
from deepspeed_tpu.ops.kernels.flash_attention import attention_reference
from deepspeed_tpu.ops.kernels.fused_optimizer import adamw_reference


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("t", [128, 80])  # 80 exercises padding+mask
    def test_forward_parity(self, causal, t):
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
        q = _rand(k1, (2, t, 2, 32))
        k = _rand(k2, (2, t, 2, 32))
        v = _rand(k3, (2, t, 2, 32))
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gqa_forward(self):
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
        q = _rand(k1, (1, 128, 4, 16))
        k = _rand(k2, (1, 128, 2, 16))
        v = _rand(k3, (1, 128, 2, 16))
        out = flash_attention(q, k, v, causal=True, interpret=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("t", [128, 80])  # 80 exercises padding+mask
    def test_gqa_gradient_parity(self, causal, t):
        """GQA backward: the grouped dk/dv accumulation grid must sum a KV
        head's cotangent over its whole q-head group (4 q heads over 2 KV
        heads here), matching autodiff through the repeated reference —
        with multiple q/k blocks so the fused (q-head, q-block) inner grid
        dim is exercised across block boundaries."""
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
        q = _rand(k1, (1, t, 4, 16))
        k = _rand(k2, (1, t, 2, 16))
        v = _rand(k3, (1, t, 2, 16))

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, interpret=True,
                                block_q=64, block_k=128)
            return jnp.sum(jnp.sin(o))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(attention_reference(q, k, v, causal=causal)))

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            assert a.shape == b.shape
            np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradient_parity(self, causal):
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
        q = _rand(k1, (1, 128, 2, 16))
        k = _rand(k2, (1, 128, 2, 16))
        v = _rand(k3, (1, 128, 2, 16))

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, interpret=True)
            return jnp.sum(jnp.sin(o))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(attention_reference(q, k, v, causal=causal)))

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)

    def test_gradient_parity_padded(self):
        """Padded (non-multiple-of-block) sequence: grads must match too."""
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(3), 3)
        q = _rand(k1, (1, 72, 2, 16))
        k = _rand(k2, (1, 72, 2, 16))
        v = _rand(k3, (1, 72, 2, 16))

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           interpret=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("tq,tk", [(1, 128), (64, 256), (96, 160)])
    def test_causal_decode_alignment(self, tq, tk):
        """q_len != kv_len: causal diagonal is bottom-right aligned (decode
        over a prefix attends the whole prefix)."""
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
        q = _rand(k1, (1, tq, 2, 16))
        k = _rand(k2, (1, tk, 2, 16))
        v = _rand(k3, (1, tk, 2, 16))
        out = flash_attention(q, k, v, causal=True, interpret=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_attention_impl_validation(self):
        from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config
        cfg = GPT2Config.tiny(attention_impl="typo", dtype=jnp.float32)
        model = GPT2(cfg)
        toks = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="attention_impl"):
            model.init(jax.random.PRNGKey(0), toks)

    def test_multi_block(self):
        """Sequence spanning several KV blocks (online-softmax accumulation)."""
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(4), 3)
        q = _rand(k1, (1, 256, 1, 16))
        k = _rand(k2, (1, 256, 1, 16))
        v = _rand(k3, (1, 256, 1, 16))
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                              interpret=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


class TestNorms:
    def test_rms_norm(self):
        x = _rand(jax.random.PRNGKey(0), (64, 256))
        w = 1.0 + 0.1 * _rand(jax.random.PRNGKey(1), (256,))
        out = fused_rms_norm(x, w, interpret=True)
        ref = (x / jnp.sqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6)) * w
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_rms_norm_grad(self):
        x = _rand(jax.random.PRNGKey(2), (32, 128))
        w = 1.0 + 0.1 * _rand(jax.random.PRNGKey(3), (128,))

        def f_fused(x, w):
            return jnp.sum(fused_rms_norm(x, w, interpret=True) ** 2)

        def f_ref(x, w):
            y = (x / jnp.sqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6)) * w
            return jnp.sum(y ** 2)

        gx1, gw1 = jax.grad(f_fused, (0, 1))(x, w)
        gx2, gw2 = jax.grad(f_ref, (0, 1))(x, w)
        np.testing.assert_allclose(gx1, gx2, atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(gw1, gw2, atol=1e-4, rtol=1e-4)

    def test_layer_norm(self):
        x = _rand(jax.random.PRNGKey(4), (48, 192))
        w = 1.0 + 0.1 * _rand(jax.random.PRNGKey(5), (192,))
        b = 0.1 * _rand(jax.random.PRNGKey(6), (192,))
        out = fused_layer_norm(x, w, b, interpret=True)
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        ref = (x - mu) / jnp.sqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_layer_norm_grad(self):
        x = _rand(jax.random.PRNGKey(7), (16, 128))
        w = 1.0 + 0.1 * _rand(jax.random.PRNGKey(8), (128,))
        b = 0.1 * _rand(jax.random.PRNGKey(9), (128,))

        def f_fused(x, w, b):
            return jnp.sum(jnp.cos(fused_layer_norm(x, w, b, interpret=True)))

        def f_ref(x, w, b):
            mu = jnp.mean(x, -1, keepdims=True)
            y = (x - mu) / jnp.sqrt(jnp.var(x, -1, keepdims=True) + 1e-5)
            return jnp.sum(jnp.cos(y * w + b))

        g1 = jax.grad(f_fused, (0, 1, 2))(x, w, b)
        g2 = jax.grad(f_ref, (0, 1, 2))(x, w, b)
        for a, c in zip(g1, g2):
            np.testing.assert_allclose(a, c, atol=1e-4, rtol=1e-4)

    def test_bf16_io_f32_stats(self):
        x = _rand(jax.random.PRNGKey(10), (32, 128)).astype(jnp.bfloat16)
        w = jnp.ones((128,), jnp.bfloat16)
        out = fused_rms_norm(x, w, interpret=True)
        assert out.dtype == jnp.bfloat16


class TestQuantization:
    @pytest.mark.parametrize("bits,tol", [(8, 0.02), (4, 0.35)])
    @pytest.mark.parametrize("symmetric", [True, False])
    def test_round_trip(self, bits, tol, symmetric):
        x = _rand(jax.random.PRNGKey(0), (1024,)) * 3.0
        qt = quantize_blockwise(x, bits=bits, group_size=128,
                                symmetric=symmetric, interpret=True)
        out = dequantize_blockwise(qt)
        err = float(jnp.max(jnp.abs(out - x)))
        scale_mag = float(jnp.max(jnp.abs(x)))
        assert err < tol * scale_mag, err

    def test_non_divisible_length(self):
        x = _rand(jax.random.PRNGKey(1), (1000,))
        out = quant_dequant(x, bits=8, group_size=128, interpret=True)
        assert out.shape == x.shape
        assert float(jnp.max(jnp.abs(out - x))) < 0.1

    def test_shape_preserved(self):
        x = _rand(jax.random.PRNGKey(2), (8, 32, 16))
        out = quant_dequant(x, bits=8, group_size=64, interpret=True)
        assert out.shape == x.shape

    def test_int4_packing_halves_bytes(self):
        x = _rand(jax.random.PRNGKey(3), (512,))
        q8 = quantize_blockwise(x, bits=8, group_size=128, interpret=True)
        q4 = quantize_blockwise(x, bits=4, group_size=128, interpret=True)
        assert q4.values.size == q8.values.size // 2


class TestFusedAdamW:
    @pytest.mark.parametrize("n", [1024, 1000])  # 1000 exercises padding
    def test_parity_with_reference(self, n):
        keys = jax.random.split(jax.random.PRNGKey(0), 4)
        p = _rand(keys[0], (n,))
        g = _rand(keys[1], (n,))
        m = 0.1 * _rand(keys[2], (n,))
        v = jnp.abs(0.1 * _rand(keys[3], (n,)))
        kw = dict(lr=1e-3, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
        p1, m1, v1 = fused_adamw_update(p, g, m, v, 3, interpret=True, **kw)
        p2, m2, v2 = adamw_reference(p, g, m, v, 3, **kw)
        np.testing.assert_allclose(p1, p2, atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(m1, m2, atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(v1, v2, atol=1e-6, rtol=1e-6)

    def test_multi_step_matches_optax_adamw(self):
        import optax
        n = 512
        p = _rand(jax.random.PRNGKey(1), (n,))
        tx = optax.adamw(1e-3, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
        state = tx.init(p)
        p_opx = p
        p_fused = p
        m = jnp.zeros((n,))
        v = jnp.zeros((n,))
        for t in range(1, 4):
            g = _rand(jax.random.PRNGKey(10 + t), (n,))
            upd, state = tx.update(g, state, p_opx)
            p_opx = optax.apply_updates(p_opx, upd)
            p_fused, m, v = fused_adamw_update(
                p_fused, g, m, v, t, lr=1e-3, weight_decay=0.01,
                interpret=True)
        np.testing.assert_allclose(p_fused, p_opx, atol=1e-5, rtol=1e-5)


class TestFlashAttentionSparse:
    """Block-sparse flash path (splash-style grid skipping)."""

    def _ref(self, q, k, v, bm, block=128):
        mask = np.kron(np.asarray(bm, bool),
                       np.ones((block, block), dtype=bool))[:, :q.shape[2],
                                                            :k.shape[2]]
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / np.sqrt(q.shape[-1])
        s = jnp.where(jnp.asarray(mask)[None], s,
                      float(np.finfo(np.float32).min))
        p = jax.nn.softmax(s, axis=-1)
        p = jnp.where(jnp.asarray(mask)[None].any(-1, keepdims=True), p, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))

    def test_matches_masked_reference(self):
        from deepspeed_tpu.ops.kernels import flash_attention_sparse
        rng = jax.random.PRNGKey(0)
        b, h, t, d = 2, 2, 384, 64            # 3x3 blocks of 128
        q = jax.random.normal(rng, (b, h, t, d), jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (b, h, t, d))
        v = jax.random.normal(jax.random.PRNGKey(2), (b, h, t, d))
        bm = np.array([[[1, 0, 1], [0, 1, 0], [1, 1, 1]],
                       [[1, 1, 0], [1, 0, 1], [0, 0, 1]]], np.int32)
        out = flash_attention_sparse(q, k, v, bm, layout="BHTD",
                                     interpret=True)
        ref = self._ref(q, k, v, bm)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_fully_masked_row_is_zero(self):
        from deepspeed_tpu.ops.kernels import flash_attention_sparse
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 256, 64))
        bm = np.array([[[1, 1], [0, 0]]], np.int32)   # row block 1: nothing
        out = flash_attention_sparse(q, q, q, bm, layout="BHTD",
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(out[:, :, 128:]), 0.0)
        assert float(jnp.abs(out[:, :, :128]).max()) > 0

    def test_sparse_attention_flash_impl(self):
        from deepspeed_tpu.ops.sparse_attention import (
            BigBirdSparsityConfig, sparse_attention)
        # 128-block layout re-tiles exactly — the flash path applies it
        cfg = BigBirdSparsityConfig(num_heads=2, block=128,
                                    num_sliding_window_blocks=1,
                                    num_global_blocks=1)
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 384, 32))
        layout = cfg.make_layout(384)
        out = sparse_attention(q, q, q, cfg, layout=layout, impl="flash")
        ref = sparse_attention(q, q, q, cfg, layout=layout)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_flash_impl_rejects_inexact_and_token_masks(self):
        from deepspeed_tpu.ops.sparse_attention import (
            FixedSparsityConfig, sparse_attention)
        # fine causal layout: coarsening would add (future) attention
        cfg = FixedSparsityConfig(num_heads=1, block=16,
                                  attention="unidirectional")
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 256, 32))
        with pytest.raises(ValueError, match="128-block"):
            sparse_attention(q, q, q, cfg, impl="flash")
        with pytest.raises(ValueError, match="layout_mask"):
            sparse_attention(q, q, q, cfg, impl="flash",
                             layout_mask=jnp.ones((1, 256, 256), bool))

    def test_coarsen_layout(self):
        from deepspeed_tpu.ops.sparse_attention import (
            coarsen_layout, coarsening_is_exact)
        fine = np.zeros((1, 16, 16), bool)
        fine[0, 3, 9] = True                  # one 16-block hit
        coarse = coarsen_layout(fine, 16, 128)
        assert coarse.shape == (1, 2, 2)
        assert coarse[0, 0, 1] and coarse.sum() == 1
        assert not coarsening_is_exact(fine, 16)   # partial block -> inexact
        # fully-dense coarse blocks are exact
        fine2 = np.zeros((1, 16, 16), bool)
        fine2[0, :8, 8:] = True
        assert coarsening_is_exact(fine2, 16)
        # expansion (block > 128) is exact by repetition
        big = np.asarray([[[1, 0], [0, 1]]], bool)
        exp = coarsen_layout(big, 256, 128)
        assert exp.shape == (1, 4, 4)
        assert exp[0, 0, 0] and exp[0, 1, 1] and not exp[0, 0, 2]


class TestShardedFlash:
    """sharded_flash_attention: the DP/ZeRO/TP shard_map wrapping."""

    def test_batch_and_head_sharded(self, devices8):
        from deepspeed_tpu.config import MeshConfig
        from deepspeed_tpu.ops.kernels import sharded_flash_attention
        from deepspeed_tpu.parallel import build_mesh
        topo = build_mesh(MeshConfig(data=4, model=2))
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(x, (8, 32, 4, 16), jnp.float32)
                   for x in ks)
        ref = attention_reference(q, k, v, causal=True)
        out = sharded_flash_attention(q, k, v, topo.mesh, causal=True,
                                      interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)

    def test_indivisible_falls_back(self, devices8):
        from deepspeed_tpu.config import MeshConfig
        from deepspeed_tpu.ops.kernels import sharded_flash_attention
        from deepspeed_tpu.parallel import build_mesh
        topo = build_mesh(MeshConfig(data=8))
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        # batch 3 not divisible by data=8 -> unsharded kernel fallback
        q, k, v = (jax.random.normal(x, (3, 16, 2, 8), jnp.float32)
                   for x in ks)
        ref = attention_reference(q, k, v, causal=True)
        out = sharded_flash_attention(q, k, v, topo.mesh, causal=True,
                                      interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)

    def test_grad_matches_reference(self, devices8):
        from deepspeed_tpu.config import MeshConfig
        from deepspeed_tpu.ops.kernels import sharded_flash_attention
        from deepspeed_tpu.parallel import build_mesh
        topo = build_mesh(MeshConfig(data=2, model=2, seq=2))
        ks = jax.random.split(jax.random.PRNGKey(2), 3)
        q, k, v = (jax.random.normal(x, (4, 32, 4, 8), jnp.float32)
                   for x in ks)

        def loss_kernel(q, k, v):
            o = sharded_flash_attention(q, k, v, topo.mesh, causal=True,
                                        interpret=True)
            return jnp.sum(o ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

        gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=1e-3)

    def test_lse_output_grad(self):
        """return_lse: the lse cotangent folds into the backward
        (delta - dlse) — check against autodiff of a jnp logsumexp."""
        from deepspeed_tpu.ops.kernels import flash_attention
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q, k, v = (jax.random.normal(x, (1, 16, 2, 8), jnp.float32)
                   for x in ks)
        sm = 1.0 / np.sqrt(8)

        def loss_kernel(q, k, v):
            o, lse = flash_attention(q, k, v, causal=True, interpret=True,
                                     return_lse=True)
            return jnp.sum(o) + jnp.sum(jnp.sin(lse))

        def loss_ref(q, k, v):
            qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
            s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * sm
            mask = jnp.tril(jnp.ones((16, 16), bool))
            s = jnp.where(mask, s, -jnp.inf)
            o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vt)
            lse = jax.nn.logsumexp(s, axis=-1)
            return jnp.sum(jnp.swapaxes(o, 1, 2)) + jnp.sum(jnp.sin(lse))

        np.testing.assert_allclose(float(loss_kernel(q, k, v)),
                                   float(loss_ref(q, k, v)), rtol=1e-5)
        gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=1e-3)


class TestFusedXent:
    """Streaming LM-head cross-entropy (ops/kernels/fused_xent.py): loss
    and both gradients must match the chunked reference exactly — the
    kernel recomputes identical logits tiles, so the only difference is
    f32 summation order."""

    def _data(self, B=2, T=24, C=64, V=300):
        rng = np.random.RandomState(0)
        h = jnp.asarray(rng.randn(B, T, C) * 0.5, jnp.float32)
        emb = jnp.asarray(rng.randn(V, C) * 0.2, jnp.float32)
        tgt = jnp.asarray(rng.randint(0, V, size=(B, T)), jnp.int32)
        return h, emb, tgt

    def test_loss_and_grads_match_chunked(self):
        from deepspeed_tpu.models._lm_utils import chunked_lm_xent
        from deepspeed_tpu.ops.kernels import fused_lm_xent
        h, emb, tgt = self._data()
        ref = chunked_lm_xent(h, emb, tgt, num_chunks=4)
        got = fused_lm_xent(h, emb, tgt, token_block=16, vocab_block=128,
                            interpret=True)
        assert abs(float(ref) - float(got)) < 1e-4
        gr = jax.grad(lambda a, b: chunked_lm_xent(a, b, tgt, 4), (0, 1))(
            h, emb)
        gg = jax.grad(lambda a, b: fused_lm_xent(
            a, b, tgt, token_block=16, vocab_block=128, interpret=True),
            (0, 1))(h, emb)
        for a, b in zip(gr, gg):
            d = float(jnp.max(jnp.abs(a - b))) / float(jnp.max(jnp.abs(a)))
            assert d < 1e-3

    def test_token_padding_excluded(self):
        # N not a multiple of token_block: padded rows must not leak into
        # the loss or the embedding gradient
        from deepspeed_tpu.models._lm_utils import chunked_lm_xent
        from deepspeed_tpu.ops.kernels import fused_lm_xent
        h, emb, tgt = self._data(T=19)
        ref = chunked_lm_xent(h, emb, tgt, num_chunks=1)
        got = fused_lm_xent(h, emb, tgt, token_block=16, vocab_block=128,
                            interpret=True)
        assert abs(float(ref) - float(got)) < 1e-4
        gr = jax.grad(lambda a, b: chunked_lm_xent(a, b, tgt, 1), (0, 1))(
            h, emb)
        gg = jax.grad(lambda a, b: fused_lm_xent(
            a, b, tgt, token_block=16, vocab_block=128, interpret=True),
            (0, 1))(h, emb)
        for a, b in zip(gr, gg):       # dh exercises the padded-row slice
            d = float(jnp.max(jnp.abs(a - b))) / float(jnp.max(jnp.abs(a)))
            assert d < 1e-3
            assert np.isfinite(np.asarray(b)).all()

    def test_bf16_inputs(self):
        from deepspeed_tpu.models._lm_utils import chunked_lm_xent
        from deepspeed_tpu.ops.kernels import fused_lm_xent
        h, emb, tgt = self._data()
        ref = chunked_lm_xent(h, emb, tgt, num_chunks=4)
        got = fused_lm_xent(h.astype(jnp.bfloat16), emb.astype(jnp.bfloat16),
                            tgt, token_block=16, vocab_block=128,
                            interpret=True)
        assert abs(float(ref) - float(got)) < 0.05

    def test_model_config_routes_fused(self):
        # GPT2Config(xent_impl="fused") trains through the kernel path
        from deepspeed_tpu.models.gpt2 import GPT2Config, make_model
        cfg = GPT2Config(vocab_size=96, max_seq_len=17, num_layers=1,
                         num_heads=2, hidden_size=32, dtype=jnp.float32,
                         xent_impl="fused")
        model, init_fn, loss_fn = make_model(cfg)
        params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=16)
        batch = {"tokens": jnp.asarray(
            np.random.RandomState(0).randint(0, 96, size=(2, 17)),
            jnp.int32)}
        loss, grads = jax.value_and_grad(loss_fn)(params, batch,
                                                  jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))
        gnorm = sum(float(jnp.sum(g * g))
                    for g in jax.tree_util.tree_leaves(grads))
        assert gnorm > 0

    def test_ignore_index(self):
        # torch cross_entropy ignore_index semantics: dropped from loss,
        # divisor, and BOTH gradients, in both implementations
        from deepspeed_tpu.models._lm_utils import chunked_lm_xent
        from deepspeed_tpu.ops.kernels import fused_lm_xent
        h, emb, tgt = self._data(T=20)
        mask = np.zeros((2, 20), bool)
        mask[0, 3:7] = True
        mask[1, -5:] = True
        tgt_ig = jnp.where(jnp.asarray(mask), -100, tgt)

        # reference: mean over kept positions only
        logits = (h.astype(jnp.float32)
                  @ emb.astype(jnp.float32).T)
        lse = jax.nn.logsumexp(logits, axis=-1)
        t_c = jnp.clip(tgt_ig, 0, emb.shape[0] - 1)
        nll = lse - jnp.take_along_axis(logits, t_c[..., None], -1)[..., 0]
        want = float(jnp.where(tgt_ig == -100, 0, nll).sum()
                     / (~mask).sum())

        got_c = chunked_lm_xent(h, emb, tgt_ig, num_chunks=4,
                                ignore_index=-100)
        got_f = fused_lm_xent(h, emb, tgt_ig, token_block=16,
                              vocab_block=128, ignore_index=-100,
                              interpret=True)
        assert abs(float(got_c) - want) < 1e-4
        assert abs(float(got_f) - want) < 1e-4

        # gradients: zero flow through ignored positions
        gh_c, ge_c = jax.grad(lambda a, b: chunked_lm_xent(
            a, b, tgt_ig, 4, ignore_index=-100), (0, 1))(h, emb)
        gh_f, ge_f = jax.grad(lambda a, b: fused_lm_xent(
            a, b, tgt_ig, token_block=16, vocab_block=128,
            ignore_index=-100, interpret=True), (0, 1))(h, emb)
        m3 = jnp.asarray(mask)[..., None]
        assert float(jnp.abs(jnp.where(m3, gh_f, 0)).max()) == 0.0
        for a, b in ((gh_c, gh_f), (ge_c, ge_f)):
            d = float(jnp.max(jnp.abs(a - b))) / float(jnp.max(jnp.abs(a)))
            assert d < 1e-3

    def test_out_of_range_ids_excluded(self):
        # corrupt labels (>= V, or >= the padded vocab grid) must not
        # poison the loss (ADVICE r4: the -inf masked column), must carry
        # zero gradient, and both impls must agree — torch raises here;
        # we exclude from loss + divisor (documented divergence)
        from deepspeed_tpu.models._lm_utils import chunked_lm_xent
        from deepspeed_tpu.ops.kernels import fused_lm_xent
        h, emb, tgt = self._data(T=20, V=300)
        bad = np.zeros((2, 20), bool)
        bad[0, 2] = bad[0, 11] = bad[1, 0] = True
        # 305 lands inside the padded vocab tile ([V, Vt*Vb)); 7000 is
        # beyond the whole padded grid — both failure modes from ADVICE
        tgt_bad = jnp.asarray(
            np.where(bad, np.array([[305] * 20, [7000] * 20]), tgt),
            jnp.int32)

        logits = h.astype(jnp.float32) @ emb.astype(jnp.float32).T
        lse = jax.nn.logsumexp(logits, axis=-1)
        t_c = jnp.clip(tgt_bad, 0, emb.shape[0] - 1)
        nll = lse - jnp.take_along_axis(logits, t_c[..., None], -1)[..., 0]
        want = float(jnp.where(jnp.asarray(bad), 0, nll).sum()
                     / (~bad).sum())

        got_c = chunked_lm_xent(h, emb, tgt_bad, num_chunks=4)
        got_f = fused_lm_xent(h, emb, tgt_bad, token_block=16,
                              vocab_block=128, interpret=True)
        assert np.isfinite(float(got_c)) and np.isfinite(float(got_f))
        assert abs(float(got_c) - want) < 1e-4
        assert abs(float(got_f) - want) < 1e-4

        gh_c, ge_c = jax.grad(lambda a, b: chunked_lm_xent(
            a, b, tgt_bad, 4), (0, 1))(h, emb)
        gh_f, ge_f = jax.grad(lambda a, b: fused_lm_xent(
            a, b, tgt_bad, token_block=16, vocab_block=128,
            interpret=True), (0, 1))(h, emb)
        m3 = jnp.asarray(bad)[..., None]
        assert float(jnp.abs(jnp.where(m3, gh_f, 0)).max()) == 0.0
        assert np.isfinite(np.asarray(gh_f)).all()
        assert np.isfinite(np.asarray(ge_f)).all()
        for a, b in ((gh_c, gh_f), (ge_c, ge_f)):
            d = float(jnp.max(jnp.abs(a - b))) / float(jnp.max(jnp.abs(a)))
            assert d < 1e-3

    def test_z_loss(self):
        # PaLM-style z-loss: loss + z*lse^2 per position, gradients via
        # the in-kernel (1 + 2z*lse)*P - onehot factor — checked against
        # autodiff of the explicit formula
        from deepspeed_tpu.ops.kernels import fused_lm_xent
        h, emb, tgt = self._data()
        z = 1e-2

        def ref_loss(a, b):
            logits = (a.astype(jnp.float32).reshape(-1, a.shape[-1])
                      @ b.astype(jnp.float32).T)
            lse = jax.nn.logsumexp(logits, axis=-1)
            t = tgt.reshape(-1)
            nll = lse - jnp.take_along_axis(
                logits, t[:, None], axis=-1)[:, 0]
            return (nll + z * lse * lse).mean()

        want = ref_loss(h, emb)
        got = fused_lm_xent(h, emb, tgt, token_block=16, vocab_block=128,
                            z_loss=z, interpret=True)
        assert abs(float(want) - float(got)) < 1e-4
        gr = jax.grad(ref_loss, (0, 1))(h, emb)
        gg = jax.grad(lambda a, b: fused_lm_xent(
            a, b, tgt, token_block=16, vocab_block=128, z_loss=z,
            interpret=True), (0, 1))(h, emb)
        for a, b in zip(gr, gg):
            d = float(jnp.max(jnp.abs(a - b))) / float(jnp.max(jnp.abs(a)))
            assert d < 1e-3

    def test_label_smoothing(self):
        # smoothed target distribution (1-eps)*onehot + eps/V — loss and
        # both gradients vs autodiff of the explicit formula
        from deepspeed_tpu.ops.kernels import fused_lm_xent
        h, emb, tgt = self._data()
        eps = 0.1

        def ref_loss(a, b):
            logits = (a.astype(jnp.float32).reshape(-1, a.shape[-1])
                      @ b.astype(jnp.float32).T)
            logp = jax.nn.log_softmax(logits, axis=-1)
            t = tgt.reshape(-1)
            V = b.shape[0]
            q = (1 - eps) * jax.nn.one_hot(t, V) + eps / V
            return -(q * logp).sum(-1).mean()

        want = ref_loss(h, emb)
        got = fused_lm_xent(h, emb, tgt, token_block=16, vocab_block=128,
                            label_smoothing=eps, interpret=True)
        assert abs(float(want) - float(got)) < 1e-4
        gr = jax.grad(ref_loss, (0, 1))(h, emb)
        gg = jax.grad(lambda a, b: fused_lm_xent(
            a, b, tgt, token_block=16, vocab_block=128,
            label_smoothing=eps, interpret=True), (0, 1))(h, emb)
        for a, b in zip(gr, gg):
            d = float(jnp.max(jnp.abs(a - b))) / float(jnp.max(jnp.abs(a)))
            assert d < 1e-3

    def test_sharded_wrapper_matches_chunked(self, devices8):
        # shard_map wrapping (rows over data, emb replicated, psum'd
        # loss): values AND both grads — incl. the psum'd embedding
        # cotangent and per-shard ignore_index counts — must match
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deepspeed_tpu.models._lm_utils import chunked_lm_xent
        from deepspeed_tpu.ops.kernels.fused_xent import (
            sharded_fused_lm_xent)
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
        rng = np.random.RandomState(0)
        B, T, C, V = 16, 24, 64, 300
        h = jnp.asarray(rng.randn(B, T, C) * 0.5, jnp.float32)
        emb = jnp.asarray(rng.randn(V, C) * 0.2, jnp.float32)
        tgt = jnp.asarray(rng.randint(0, V, size=(B, T)), jnp.int32)
        # shard 0's rows are ENTIRELY ignored: the divisor must be the
        # global valid count (a per-shard clamp would inflate it by 1)
        tgt = tgt.at[0].set(-100)
        tgt = tgt.at[1].set(-100)
        h = jax.device_put(h, NamedSharding(mesh, P("data")))
        tgt = jax.device_put(tgt, NamedSharding(mesh, P("data")))
        emb = jax.device_put(emb, NamedSharding(mesh, P()))

        def loss_sh(h_, e_):
            return sharded_fused_lm_xent(
                h_, e_, tgt, mesh, token_block=16, vocab_block=128,
                ignore_index=-100, interpret=True)

        def loss_ref(h_, e_):
            return chunked_lm_xent(h_, e_, tgt, num_chunks=4,
                                   ignore_index=-100)

        assert abs(float(jax.jit(loss_sh)(h, emb))
                   - float(jax.jit(loss_ref)(h, emb))) < 1e-4
        g1 = jax.jit(jax.grad(loss_sh, argnums=(0, 1)))(h, emb)
        g2 = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(h, emb)
        for a, b in zip(g1, g2):
            d = float(jnp.max(jnp.abs(a - b))) / float(jnp.max(jnp.abs(b)))
            assert d < 1e-3


class TestFp6Gemm:
    """Fused FP6 weight-only GEMM (ops/kernels/fp6_gemm.py) — the
    reference's FP6 serving path (inference/v2/kernels/core_ops/
    cuda_linear/), TPU form."""

    def _w(self, K=256, N=512, seed=0):
        return jax.random.normal(jax.random.PRNGKey(seed), (K, N),
                                 jnp.float32) * 0.1

    def test_pack_unpack_quantization_error(self):
        from deepspeed_tpu.ops.kernels import fp6_gemm_pack, fp6_gemm_unpack
        w = self._w()
        wq = fp6_gemm_unpack(fp6_gemm_pack(w))
        assert wq.shape == w.shape
        # e3m2 with per-column scaling: ~2 mantissa bits => relative
        # error bounded by ~2^-3 of the column max
        colmax = jnp.max(jnp.abs(w), axis=0)
        err = jnp.max(jnp.abs(wq - w) / colmax[None, :])
        assert float(err) < 0.14, float(err)

    def test_matmul_matches_unpacked(self):
        from deepspeed_tpu.ops.kernels import (fp6_gemm_pack,
                                               fp6_gemm_unpack, fp6_matmul)
        w = self._w()
        fw = fp6_gemm_pack(w)
        x = jax.random.normal(jax.random.PRNGKey(1), (24, 256), jnp.float32)
        ref = x @ fp6_gemm_unpack(fw)
        got = fp6_matmul(x, fw, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=3e-4, rtol=3e-4)

    def test_batched_and_padded_rows(self):
        from deepspeed_tpu.ops.kernels import (fp6_gemm_pack,
                                               fp6_gemm_unpack, fp6_matmul)
        fw = fp6_gemm_pack(self._w())
        x = jax.random.normal(jax.random.PRNGKey(2), (3, 5, 256),
                              jnp.float32)          # M=15: pads to tile
        ref = x @ fp6_gemm_unpack(fw)
        got = fp6_matmul(x, fw, interpret=True)
        assert got.shape == (3, 5, 512)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=3e-4, rtol=3e-4)

    def test_unaligned_falls_back(self):
        from deepspeed_tpu.ops.kernels import (fp6_gemm_pack,
                                               fp6_gemm_unpack, fp6_matmul)
        w = self._w(K=100, N=40)                    # no 128-divisor tiles
        fw = fp6_gemm_pack(w)
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 100), jnp.float32)
        ref = x @ fp6_gemm_unpack(fw)
        got = fp6_matmul(x, fw, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5)

    def test_storage_is_6_bits(self):
        from deepspeed_tpu.ops.kernels import fp6_gemm_pack
        fw = fp6_gemm_pack(self._w(K=256, N=512))
        assert fw.bytes3.dtype == jnp.uint8
        # 3 bytes per 4 values = 6 bits/value
        assert fw.bytes3.size == 256 * 512 * 6 // 8

    def test_woq_fp6_serving_dtype(self):
        # inference/quantization num_bits=6 path: FPQuantizedTensor leaves,
        # dequantize_tree view, memory accounting
        from deepspeed_tpu.inference.quantization import (
            dequantize_tree, quantize_model_params, woq_memory_bytes)
        from deepspeed_tpu.ops.fp_quantizer import FPQuantizedTensor
        params = {"proj": {"kernel": self._w(K=128, N=256)},
                  "norm": {"scale": jnp.ones((256,))}}
        q = quantize_model_params(
            params, {"quantized_weights": {"enabled": True, "num_bits": 6,
                                           "group_size": 128}})
        assert isinstance(q["proj"]["kernel"], FPQuantizedTensor)
        deq = dequantize_tree(q)
        colmax = float(jnp.max(jnp.abs(params["proj"]["kernel"])))
        assert float(jnp.max(jnp.abs(
            deq["proj"]["kernel"] - params["proj"]["kernel"]))) < 0.14 * colmax
        assert woq_memory_bytes(q) < woq_memory_bytes(params) / 2


class TestFusedFp6Serving:
    """fused_gemm WOQ through the ragged engine: Fp6GemmWeight leaves
    survive the in-jit dequant pass and llama_runner's woq_mm dispatch
    streams them through the fused kernel (eligible shapes) or the
    unpack fallback (small projections)."""

    def _engine(self, fused):
        from deepspeed_tpu.inference.quantization import (
            quantize_model_params, woq_memory_bytes)
        from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                                RaggedInferenceConfig)
        from deepspeed_tpu.models.llama import Llama, LlamaConfig

        mcfg = LlamaConfig.tiny(dtype=jnp.float32, max_seq_len=128,
                                hidden_size=128, num_heads=4,
                                num_kv_heads=2, intermediate_size=512)
        model = Llama(mcfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
        q = quantize_model_params(
            params, {"quantized_weights": {
                "dtype": "fp6", "group_size": 64, "fused_gemm": fused,
                "excluded_modules": ["embed", "norm", "lm_head"]}})
        cfg = RaggedInferenceConfig(max_seqs=2, chunk_size=8, block_size=64,
                                    num_blocks=8, max_blocks_per_seq=1,
                                    dtype="float32")
        return InferenceEngineV2(mcfg, q, cfg), q, woq_memory_bytes

    def test_fused_leaves_and_generate_parity(self):
        from deepspeed_tpu.inference.quantization import dequantize_tree
        from deepspeed_tpu.inference.v2 import InferenceEngineV2
        from deepspeed_tpu.ops.kernels import Fp6GemmWeight
        eng_f, qf, _ = self._engine(fused=True)
        # the wide MLP kernels really are in the fused layout
        mlp = qf["layer_0"]["mlp"]["gate_proj"]["kernel"]
        assert isinstance(mlp, Fp6GemmWeight)

        # parity against the SAME fused tree served dense (the generic
        # fp6 engine quantizes with different scale groups, so its
        # trajectory is a different model — not the comparison)
        dense_same = dequantize_tree(qf)
        eng_ref = InferenceEngineV2(eng_f.runner.model_cfg, dense_same,
                                    eng_f.config)
        prompt = list(np.random.default_rng(0).integers(1, 512, 12))
        got_f = eng_f.generate([prompt], max_new_tokens=5)[0]
        got_r = eng_ref.generate([prompt], max_new_tokens=5)[0]
        # identical decoded values, different accumulation order: greedy
        # trajectories must agree at least on the first tokens
        assert got_f[:2] == got_r[:2], (got_f, got_r)

    def test_fused_moe_router_survives(self):
        # Mixtral's router weight [hidden, E] is fused-packable; the MoE
        # path must unpack it rather than crash (review r5 finding)
        from deepspeed_tpu.inference.quantization import (
            quantize_model_params)
        from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                                RaggedInferenceConfig)
        from deepspeed_tpu.models.mixtral import Mixtral, MixtralConfig
        mcfg = MixtralConfig.tiny(dtype=jnp.float32, max_seq_len=128,
                                  hidden_size=128, num_heads=4,
                                  num_kv_heads=2, intermediate_size=512,
                                  num_experts=4)
        model = Mixtral(mcfg)
        k = jax.random.PRNGKey(0)
        params = model.init({"params": k, "gating": k},
                            jnp.zeros((1, 8), jnp.int32))["params"]
        q = quantize_model_params(
            params, {"quantized_weights": {
                "dtype": "fp6", "fused_gemm": True,
                "excluded_modules": ["embed", "norm", "lm_head"]}})
        eng = InferenceEngineV2(mcfg, q, RaggedInferenceConfig(
            max_seqs=2, chunk_size=8, block_size=64, num_blocks=8,
            max_blocks_per_seq=1, dtype="float32"))
        out = eng.generate([[5, 6, 7, 8]], max_new_tokens=3)[0]
        assert len(out) == 3

    def test_fused_non_fp6_rejected(self):
        from deepspeed_tpu.inference.quantization import (
            quantize_model_params)
        for bad in ({"dtype": "fp8", "fused_gemm": True},
                    {"num_bits": 8, "fused_gemm": True}):
            with pytest.raises(ValueError, match="fused_gemm"):
                quantize_model_params(
                    {"k": jnp.ones((8, 8))}, {"quantized_weights": bad})

    def test_plain_consumers_get_dense(self):
        # default dequantize_tree (no keep_fused) unpacks fused leaves
        from deepspeed_tpu.inference.quantization import dequantize_tree
        from deepspeed_tpu.ops.kernels import (Fp6GemmWeight,
                                               fp6_gemm_pack)
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 128))
        tree = {"k": fp6_gemm_pack(w)}
        out = dequantize_tree(tree)
        assert not isinstance(out["k"], Fp6GemmWeight)
        assert out["k"].shape == (64, 128)
        kept = dequantize_tree(tree, keep_fused=True)
        assert isinstance(kept["k"], Fp6GemmWeight)
