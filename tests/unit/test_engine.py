"""End-to-end engine tests — the analogue of reference
tests/unit/runtime/zero/test_zero.py correctness-vs-DDP-baseline tests:
every ZeRO stage must produce the same loss trajectory as stage 0, and
training must actually learn on a toy LM task."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.models.gpt2 import GPT2Config, make_model


def _toy_setup(zero_stage=0, dtype_block=None, gas=1, micro=2, extra=None):
    cfg_model = GPT2Config.tiny(dtype=jnp.float32)
    model, init_fn, loss_fn = make_model(cfg_model)
    params = init_fn(jax.random.PRNGKey(0), batch_size=2, seq_len=17)
    config = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": zero_stage},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000,
    }
    if dtype_block:
        config.update(dtype_block)
    if extra:
        config.update(extra)
    engine, _, _, _ = dstpu.initialize(loss_fn=loss_fn, params=params, config=config)
    return engine


def _batches(engine, n, seed=0):
    rng = np.random.RandomState(seed)
    B = engine.config.train_batch_size
    for _ in range(n):
        yield {"tokens": jnp.asarray(rng.randint(0, 512, size=(B, 18)), jnp.int32)}


def test_loss_decreases():
    engine = _toy_setup()
    batch = next(_batches(engine, 1))
    losses = [float(engine.train_batch(batch)) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.7, f"no learning: {losses[0]} -> {losses[-1]}"


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_matches_stage0(stage):
    """ZeRO is a memory layout, not a different algorithm: loss trajectories
    must match plain DP bit-for-bit-ish."""
    e0 = _toy_setup(zero_stage=0)
    e1 = _toy_setup(zero_stage=stage)
    for batch in _batches(e0, 5):
        l0 = float(e0.train_batch(batch))
        l1 = float(e1.train_batch(batch))
        assert abs(l0 - l1) < 1e-4, f"stage {stage} diverged: {l0} vs {l1}"


def test_grad_accumulation_equivalence():
    """gas=4 × micro=2 must match gas=1 × micro=8 on the same global batch."""
    e_a = _toy_setup(gas=1, micro=8)
    e_b = _toy_setup(gas=4, micro=2)
    assert e_a.config.train_batch_size == e_b.config.train_batch_size
    for batch in _batches(e_a, 4):
        la = float(e_a.train_batch(batch))
        lb = float(e_b.train_batch(batch))
        assert abs(la - lb) < 1e-3, f"GAS mismatch: {la} vs {lb}"


def test_bf16_training():
    engine = _toy_setup(dtype_block={"bf16": {"enabled": True}})
    batch = next(_batches(engine, 1))
    losses = [float(engine.train_batch(batch)) for _ in range(15)]
    assert losses[-1] < losses[0]


def test_fp16_dynamic_loss_scale():
    engine = _toy_setup(dtype_block={
        "fp16": {"enabled": True, "initial_scale_power": 8, "loss_scale_window": 4}})
    assert engine.get_loss_scale() == 2.0 ** 8
    batch = next(_batches(engine, 1))
    for _ in range(6):
        engine.train_batch(batch)
    # after 4+ clean steps the window doubles the scale at least once
    assert engine.get_loss_scale() >= 2.0 ** 8


def test_forward_backward_step_trio():
    engine = _toy_setup(gas=2, micro=2)
    batches = list(_batches(engine, 1))
    b = batches[0]
    half = engine.config.train_batch_size // 2
    mb1 = {"tokens": b["tokens"][:half]}
    mb2 = {"tokens": b["tokens"][half:]}
    engine.forward(mb1)
    engine.backward()
    assert engine.step() is None            # not at boundary yet
    engine.forward(mb2)
    engine.backward()
    loss = engine.step()
    assert loss is not None and float(loss) > 0
    assert engine.global_steps == 1


def test_wrong_batch_size_raises():
    engine = _toy_setup(micro=2)
    with pytest.raises(Exception):
        engine.train_batch({"tokens": jnp.zeros((3, 18), jnp.int32)})


def test_lr_schedule_applied():
    engine = _toy_setup(extra={
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                                 "warmup_num_steps": 10, "warmup_type": "linear"}}})
    batch = next(_batches(engine, 1))
    engine.train_batch(batch)
    lr1 = engine.get_lr()[0]
    for _ in range(5):
        engine.train_batch(batch)
    lr2 = engine.get_lr()[0]
    assert lr2 > lr1 > 0


def test_state_sharded_stage3(devices8):
    engine = _toy_setup(extra={
        "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0}})
    shardings = engine._state_shardings
    # at least one large param should be sharded over data
    specs = [s.spec for s in jax.tree_util.tree_leaves(
        shardings.params, is_leaf=lambda x: hasattr(x, "spec"))]
    assert any(any(p is not None for p in spec) for spec in specs)


def test_global_samples_counter():
    engine = _toy_setup()
    for batch in _batches(engine, 3):
        engine.train_batch(batch)
    assert engine.global_steps == 3
    assert engine.global_samples == 3 * engine.config.train_batch_size
