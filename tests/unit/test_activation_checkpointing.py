"""Activation checkpointing (remat) tests.

Mirrors the reference's ``tests/unit/runtime/activation_checkpointing/``:
checkpointed forward+backward must match the uncheckpointed one bit-for-bit
(same RNG), for plain fns, dropout fns, and layer stacks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.config.config import ActivationCheckpointingConfig
from deepspeed_tpu.runtime import activation_checkpointing as ac


@pytest.fixture(autouse=True)
def _reset_ac():
    yield
    ac.reset()


def _mlp(params, x):
    h = jnp.tanh(x @ params["w1"])
    return jnp.sum((h @ params["w2"]) ** 2)


def _params(key, d=16):
    k1, k2 = jax.random.split(key)
    return {"w1": jax.random.normal(k1, (d, d)) * 0.1,
            "w2": jax.random.normal(k2, (d, d)) * 0.1}


class TestCheckpoint:
    def test_grad_matches_uncheckpointed(self):
        params = _params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))

        g_ref = jax.grad(_mlp)(params, x)
        g_ckpt = jax.grad(lambda p, x_: ac.checkpoint(_mlp, p, x_))(params, x)
        for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                        jax.tree_util.tree_leaves(g_ckpt)):
            # remat reorders fusion; tolerance covers XLA-version jitter
            np.testing.assert_allclose(a, b, rtol=5e-6)

    def test_policies_resolve(self):
        for name in ("nothing_saveable", "dots_saveable", "checkpoint_dots"):
            cfg = ActivationCheckpointingConfig(policy=name)
            assert ac.resolve_policy(cfg) is not None
        with pytest.raises(ValueError):
            ac.resolve_policy(ActivationCheckpointingConfig(policy="bogus"))

    def test_cpu_checkpointing_policy(self):
        cfg = ActivationCheckpointingConfig(cpu_checkpointing=True)
        pol = ac.resolve_policy(cfg)
        assert callable(pol)
        # host-offload grad parity
        params = _params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
        g_ref = jax.grad(_mlp)(params, x)
        ac.configure(cpu_checkpointing=True)
        # host-offload policies move saved residuals with device_put-to-
        # memory-kind, an in-jit-only feature — jit like the engine does
        g = jax.jit(jax.grad(
            lambda p, x_: ac.checkpoint(_mlp, p, x_)))(params, x)
        for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                        jax.tree_util.tree_leaves(g)):
            np.testing.assert_allclose(a, b, rtol=5e-6)

    def test_configure_kwargs(self):
        cfg = ac.configure(policy="dots_saveable")
        assert cfg.policy == "dots_saveable"
        assert ac.get_config().policy == "dots_saveable"
        with pytest.raises(ValueError):
            ac.configure(not_a_knob=True)

    def test_rng_determinism_with_dropout(self):
        def dropped(params, x, key):
            h = jnp.tanh(x @ params["w1"])
            mask = jax.random.bernoulli(key, 0.5, h.shape)
            return jnp.sum(((h * mask) @ params["w2"]) ** 2)

        params = _params(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
        key = jax.random.PRNGKey(7)
        g_ref = jax.grad(dropped)(params, x, key)
        g_ckpt = jax.grad(lambda p, x_, k: ac.checkpoint(dropped, p, x_, k))(
            params, x, key)
        for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                        jax.tree_util.tree_leaves(g_ckpt)):
            np.testing.assert_allclose(a, b, rtol=1e-6)


class TestCheckpointSequential:
    def _stack(self, n_layers=4, d=8):
        keys = jax.random.split(jax.random.PRNGKey(0), n_layers)
        w = jnp.stack([jax.random.normal(k, (d, d)) * 0.1 for k in keys])
        return {"w": w}

    @staticmethod
    def _block(p, h):
        return h + jnp.tanh(h @ p["w"])

    def _ref_apply(self, stacked, x):
        h = x
        for i in range(stacked["w"].shape[0]):
            h = self._block(jax.tree_util.tree_map(lambda p: p[i], stacked), h)
        return h

    @pytest.mark.parametrize("interval", [1, 2, 4])
    def test_matches_loop(self, interval):
        stacked = self._stack()
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))

        out = ac.checkpoint_sequential(self._block, stacked, x, interval=interval)
        ref = self._ref_apply(stacked, x)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

        # gradients too
        g = jax.grad(lambda s, x_: jnp.sum(
            ac.checkpoint_sequential(self._block, s, x_, interval=interval)))(
                stacked, x)
        g_ref = jax.grad(lambda s, x_: jnp.sum(self._ref_apply(s, x_)))(stacked, x)
        np.testing.assert_allclose(g["w"], g_ref["w"], rtol=1e-5)

    def test_bad_interval(self):
        stacked = self._stack(n_layers=4)
        x = jnp.ones((2, 8))
        with pytest.raises(ValueError):
            ac.checkpoint_sequential(self._block, stacked, x, interval=3)


class TestRNGTracker:
    def test_fork_deterministic(self):
        t1 = ac.CheckpointableRNG(seed=0)
        t2 = ac.CheckpointableRNG(seed=0)
        k1, k2 = t1.fork(), t2.fork()
        np.testing.assert_array_equal(k1, k2)
        # second fork differs from first
        assert not np.array_equal(np.asarray(t1.fork()), np.asarray(k1))

    def test_states_roundtrip(self):
        t = ac.CheckpointableRNG()
        t.add("extra", 3)
        states = t.get_states()
        t.fork("extra")
        t.set_states(states)
        k_after = t.fork("extra")
        t.set_states(states)
        np.testing.assert_array_equal(k_after, t.fork("extra"))
