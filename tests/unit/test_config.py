"""Config tests — mirrors reference tests/unit/runtime/test_ds_config_dict.py."""

import json

import pytest

from deepspeed_tpu.config import Config, ConfigError


def test_defaults():
    cfg = Config.load(None)
    assert cfg.zero_optimization.stage == 0
    assert cfg.precision_dtype == "float32"
    assert cfg.gradient_clipping == 0.0


def test_ds_config_surface():
    cfg = Config.load({
        "train_batch_size": 16,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-4, "weight_decay": 0.1}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 100}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "reduce_bucket_size": 1000},
        "gradient_clipping": 1.0,
        "steps_per_print": 5,
    })
    assert cfg.optimizer.type == "AdamW"
    assert cfg.optimizer.params["lr"] == 3e-4
    assert cfg.scheduler.type == "WarmupLR"
    assert cfg.bf16.enabled and cfg.precision_dtype == "bfloat16"
    assert cfg.zero_optimization.stage == 2
    assert cfg.gradient_clipping == 1.0


def test_bool_shorthand():
    cfg = Config.load({"bf16": True})
    assert cfg.bf16.enabled


def test_json_file(tmp_path):
    p = tmp_path / "ds_config.json"
    p.write_text(json.dumps({"train_micro_batch_size_per_gpu": 4, "fp16": {"enabled": True}}))
    cfg = Config.load(str(p))
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.precision_dtype == "float16"


def test_batch_resolution_invariant():
    cfg = Config.load({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 2})
    cfg.resolve_batch_sizes(dp_world_size=4)
    assert cfg.gradient_accumulation_steps == 4
    assert cfg.train_batch_size == 2 * 4 * 4


def test_batch_resolution_micro_only():
    cfg = Config.load({"train_micro_batch_size_per_gpu": 3, "gradient_accumulation_steps": 2})
    cfg.resolve_batch_sizes(dp_world_size=8)
    assert cfg.train_batch_size == 3 * 2 * 8


def test_batch_mismatch_raises():
    cfg = Config.load({"train_batch_size": 10, "train_micro_batch_size_per_gpu": 3,
                       "gradient_accumulation_steps": 1})
    with pytest.raises(ConfigError):
        cfg.resolve_batch_sizes(dp_world_size=2)


def test_fp16_bf16_conflict():
    cfg = Config.load({"fp16": {"enabled": True}, "bf16": {"enabled": True}})
    with pytest.raises(ConfigError):
        _ = cfg.precision_dtype


def test_invalid_zero_stage():
    with pytest.raises(ConfigError):
        Config.load({"zero_optimization": {"stage": 5}})


def test_unknown_key_warns_not_raises(caplog):
    cfg = Config.load({"definitely_not_a_key": 1})
    assert isinstance(cfg, Config)


def test_roundtrip():
    cfg = Config.load({"zero_optimization": {"stage": 3}})
    d = cfg.to_dict()
    assert d["zero_optimization"]["stage"] == 3
    cfg2 = Config.from_dict(d)
    assert cfg2.zero_optimization.stage == 3
