"""On-device sampling + speculative decoding tests (ISSUE 12).

The two oracles this layer stands on:

  * **temperature→0 parity** — a sampled sequence at temperature 0 (and
    a greedy sequence riding a mixed batch through the sampler program)
    must be token-identical to the pure-greedy path, at every pipeline
    depth, through the fused loop, and under tp=2 (slow tier).
  * **speculative parity** — decode with speculation armed (ngram or a
    draft model) must be token-identical to non-speculative greedy:
    a draft token is only ever accepted where it equals greedy's own
    choice, and rejected tokens roll back through ``trim_blocks`` with
    prefix-cache refcounts exact (``PrefixCache.assert_exact_refs``).

Plus the determinism contract: sampled streams are a pure function of
(seed, position) — identical across pipeline depths, fused-vs-per-step
paths, and drain/replay restarts (the manifest carries SamplingParams).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (
    InferenceEngineV2,
    RaggedInferenceConfig,
    SamplingParams,
)
from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config

_CACHE = {}


def _gpt2(layers=2, hidden=32, key=0):
    name = f"gpt2-{layers}-{hidden}"
    if name not in _CACHE:
        mcfg = GPT2Config(vocab_size=96, max_seq_len=256, num_layers=layers,
                          num_heads=2, hidden_size=hidden,
                          dtype=jnp.float32)
        params = GPT2(mcfg).init(jax.random.PRNGKey(key),
                                 jnp.zeros((1, 8), jnp.int32))["params"]
        _CACHE[name] = (mcfg, params)
    return _CACHE[name]


def _cfg(depth=2, prefix=True, **kw):
    base = dict(max_seqs=4, chunk_size=8, block_size=4, num_blocks=96,
                max_blocks_per_seq=24, dtype="float32",
                attention_impl="dense", decode_loop_steps=0,
                serve_pipeline_depth=depth, prefix_cache=prefix)
    base.update(kw)
    return RaggedInferenceConfig(**base)


_rng = np.random.default_rng(3)
#: 3 prompts sharing a 10-token preamble (two full shared blocks at
#: block_size 4 + a CoW tail) — the shared-prefix-chain workload the
#: rollback-exactness tests need
_SHARED = _rng.integers(1, 96, 10).tolist()
PROMPTS = [_SHARED + _rng.integers(1, 96, 5).tolist() for _ in range(3)]
#: periodic prompts whose greedy continuations settle into short cycles
#: — the self-drafting (ngram) acceptance food
_PAT = _rng.integers(1, 96, 6).tolist()
REP_PROMPTS = [(_PAT * 4)[: 15 + i] for i in range(3)]
UIDS = [0, 1, 2]


def _stream(eng, prompts, n, sampling=None, uids=UIDS):
    """put + pipelined decode; returns the full per-uid streams
    (first emitted token + n continuation tokens)."""
    first = eng.put(uids, [list(p) for p in prompts], _greedy=True,
                    sampling=sampling)
    out = eng.decode_pipelined(uids, [first[u] for u in uids], n)
    return {u: [first[u]] + out[u] for u in uids}


class TestSamplingStack:
    def test_temp0_and_mixed_batch_parity_across_depths(self):
        mcfg, params = _gpt2()
        ref = _stream(InferenceEngineV2(mcfg, params, _cfg(depth=2)),
                      PROMPTS, 10)
        # uid0 explicit temperature-0 params, uid1 no params (greedy
        # rides the sampler program in the mixed batch), uid2 sampled —
        # the greedy members must be UNCHANGED by the mixed batch
        for depth in (0, 2):
            sp = {0: SamplingParams(temperature=0.0, logprobs=True),
                  2: SamplingParams(temperature=0.9, top_k=8, seed=4)}
            eng = InferenceEngineV2(mcfg, params, _cfg(depth=depth))
            got = _stream(eng, PROMPTS, 10, sampling=sp)
            assert got[0] == ref[0], f"temp0 parity broke at depth {depth}"
            assert got[1] == ref[1], f"greedy-in-mixed broke at depth {depth}"
            # a temp-0 'sampled' sequence still records logprobs
            lps = eng.logprobs_of(0)
            assert len(lps) == len(got[0]) and all(v <= 0.0 for v in lps)

    def test_seeded_streams_identical_across_paths_and_seeds(self):
        mcfg, params = _gpt2()
        sp = {u: SamplingParams(temperature=0.8, top_k=12, top_p=0.95,
                                seed=100 + u) for u in UIDS}
        runs = {}
        for label, depth, loop in (("sync", 0, 0), ("pipe2", 2, 0),
                                   ("pipe3", 3, 0), ("fused", 2, 10)):
            eng = InferenceEngineV2(mcfg, params,
                                    _cfg(depth=depth,
                                         decode_loop_steps=loop))
            first = eng.put(UIDS, [list(p) for p in PROMPTS],
                            _greedy=True, sampling=sp)
            if loop:
                out = eng.decode_batch(UIDS, [first[u] for u in UIDS], 10)
            else:
                out = eng.decode_pipelined(UIDS,
                                           [first[u] for u in UIDS], 10)
            runs[label] = {u: [first[u]] + list(out[u]) for u in UIDS}
        assert runs["sync"] == runs["pipe2"] == runs["pipe3"] \
            == runs["fused"]
        # a different seed diverges (the sampler is actually sampling)
        sp9 = {u: SamplingParams(temperature=0.8, top_k=12, top_p=0.95,
                                 seed=900 + u) for u in UIDS}
        eng = InferenceEngineV2(mcfg, params, _cfg())
        other = _stream(eng, PROMPTS, 10, sampling=sp9)
        assert other != runs["sync"]

    def test_sampled_drain_replay_restart_determinism(self):
        mcfg, params = _gpt2()
        sp = {u: SamplingParams(temperature=0.7, top_k=16, seed=7 + u)
              for u in UIDS}
        cfg = _cfg()
        want = _stream(InferenceEngineV2(mcfg, params, cfg), PROMPTS, 9,
                       sampling=sp)
        eng = InferenceEngineV2(mcfg, params, cfg)
        first = eng.put(UIDS, [list(p) for p in PROMPTS], _greedy=True,
                        sampling=sp)
        part = eng.decode_pipelined(UIDS, [first[u] for u in UIDS], 4)
        manifest = eng.drain()
        assert all(r.get("sampling") for r in manifest["sequences"])
        surv = InferenceEngineV2(mcfg, params, cfg)
        rep = surv.replay(manifest)
        cont = surv.decode_pipelined(UIDS, [rep[u] for u in UIDS], 4)
        got = {u: [first[u]] + part[u] + [rep[u]] + cont[u] for u in UIDS}
        assert got == want

    @pytest.mark.slow
    def test_journal_carries_sampling_identity(self, tmp_path):
        from deepspeed_tpu.inference.v2 import manifest_from_journal
        mcfg, params = _gpt2()
        jpath = str(tmp_path / "journal.jsonl")
        cfg = _cfg(serve_journal=jpath)
        eng = InferenceEngineV2(mcfg, params, cfg)
        sp = {0: SamplingParams(temperature=0.6, seed=42)}
        first = eng.put([0], [list(PROMPTS[0])], _greedy=True, sampling=sp)
        eng.decode_pipelined([0], [first[0]], 3)
        m = manifest_from_journal(jpath)
        rec = m["sequences"][0]
        assert rec["sampling"]["temperature"] == 0.6
        assert rec["sampling"]["seed"] == 42
        # a journal-reconstructed replay continues the SAME stream (the
        # journal's `generated` already includes the first emitted
        # token — the prefill's last-chunk commit journals it)
        want = _stream(InferenceEngineV2(mcfg, params, _cfg()),
                       [PROMPTS[0]], 7, sampling=sp, uids=[0])
        surv = InferenceEngineV2(mcfg, params, _cfg())
        rep = surv.replay(m)
        gen = list(rec["generated"])
        cont = surv.decode_pipelined(
            [0], [rep[0]], len(want[0]) - len(gen) - 1)
        got = gen + [rep[0]] + cont[0]
        assert got == want[0]

    @pytest.mark.slow
    def test_pool_passthrough_sampling(self):
        from deepspeed_tpu.serving import ReplicaPool
        mcfg, params = _gpt2()
        sp = {u: SamplingParams(temperature=0.8, top_k=8, seed=50 + u)
              for u in UIDS}
        want = _stream(InferenceEngineV2(mcfg, params, _cfg()), PROMPTS,
                       8, sampling=sp)
        pool = ReplicaPool([InferenceEngineV2(mcfg, params, _cfg())
                            for _ in range(2)], policy="round_robin")
        first = pool.put(UIDS, [list(p) for p in PROMPTS], _greedy=True,
                         sampling=sp)
        out = pool.decode_pipelined(UIDS, [first[u] for u in UIDS], 8)
        got = {u: [first[u]] + out[u] for u in UIDS}
        assert got == want


class TestSpeculativeDecode:
    def test_ngram_parity_counters_and_exact_release(self):
        mcfg, params = _gpt2()
        ref_eng = InferenceEngineV2(mcfg, params, _cfg())
        want = _stream(ref_eng, REP_PROMPTS, 12)
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(spec_decode="ngram", spec_k=4))
        got = _stream(eng, REP_PROMPTS, 12)
        assert got == want
        rep = eng.slo_report()
        assert rep["spec"]["rounds"] > 0
        assert rep["spec"]["proposed"] > 0
        assert rep["spec_accept_rate"] is not None
        assert eng.state.sequences[UIDS[0]].spec_proposed > 0
        # rejected-run rollbacks on the shared-prefix chain kept the
        # cache refcounts EXACT and the pool recovers fully
        eng._prefix.assert_exact_refs(eng.state.sequences.values())
        for u in UIDS:
            eng.flush(u)
        assert eng.kv_cache.free_blocks == eng.config.num_blocks
        eng._prefix.check_invariants()

    def test_budget_exact_and_eos_truncation(self):
        mcfg, params = _gpt2()
        ref = InferenceEngineV2(mcfg, params, _cfg())
        f0 = ref.put(UIDS, [list(p) for p in REP_PROMPTS], _greedy=True)
        budgets = [5, 9, 12]
        r0 = ref.decode_pipelined(UIDS, [f0[u] for u in UIDS], budgets)
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(spec_decode="ngram", spec_k=4))
        f1 = eng.put(UIDS, [list(p) for p in REP_PROMPTS], _greedy=True)
        r1 = eng.decode_pipelined(UIDS, [f1[u] for u in UIDS], budgets)
        assert r1 == r0
        assert [len(r1[u]) for u in UIDS] == budgets
        # eos mid-stream truncates identically
        eos = r0[UIDS[1]][2]
        ref2 = InferenceEngineV2(mcfg, params, _cfg())
        f2 = ref2.put(UIDS, [list(p) for p in REP_PROMPTS], _greedy=True)
        r2 = ref2.decode_pipelined(UIDS, [f2[u] for u in UIDS], 12,
                                   eos_token_id=eos)
        eng2 = InferenceEngineV2(mcfg, params,
                                 _cfg(spec_decode="ngram", spec_k=4))
        f3 = eng2.put(UIDS, [list(p) for p in REP_PROMPTS], _greedy=True)
        r3 = eng2.decode_pipelined(UIDS, [f3[u] for u in UIDS], 12,
                                   eos_token_id=eos)
        assert r3 == r2

    def test_noisy_proposer_rollback_refcounts_exact(self):
        # heavy rejection pressure ON a shared-prefix chain: every
        # round retracts most of its speculated span; each shared
        # block must be decref'd exactly once per release, never freed
        mcfg, params = _gpt2()
        os.environ["DSTPU_SPEC_NOISE"] = "0.6"
        try:
            eng = InferenceEngineV2(mcfg, params,
                                    _cfg(spec_decode="ngram", spec_k=4))
            want = _stream(InferenceEngineV2(mcfg, params, _cfg()),
                           PROMPTS, 10)
            got = _stream(eng, PROMPTS, 10)
        finally:
            os.environ.pop("DSTPU_SPEC_NOISE", None)
        assert got == want
        st = eng.state.prefix_stats
        assert st["trims"] > 0, "noisy speculation never rolled back"
        eng._prefix.assert_exact_refs(eng.state.sequences.values())
        for u in UIDS:
            eng.flush(u)
        assert eng.kv_cache.free_blocks == eng.config.num_blocks

    def test_draft_model_same_params_full_acceptance(self):
        mcfg, params = _gpt2()
        want = _stream(InferenceEngineV2(mcfg, params, _cfg()),
                       PROMPTS, 10)
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(spec_decode="draft", spec_k=3))
        eng.attach_draft(mcfg, params)
        got = _stream(eng, PROMPTS, 10)
        assert got == want
        rep = eng.slo_report()
        assert rep["spec_accept_rate"] == 1.0
        for u in UIDS:
            eng.flush(u)
        assert eng.kv_cache.free_blocks == eng.config.num_blocks
        assert eng._draft_engine.kv_cache.free_blocks \
            == eng._draft_engine.config.num_blocks

    def test_spec_warm_path_zero_fresh_compiles(self):
        from deepspeed_tpu.analysis import RecompileTripwire
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(spec_decode="ngram", spec_k=4))
        first = eng.put(UIDS, [list(p) for p in REP_PROMPTS],
                        _greedy=True)
        warm = eng.decode_pipelined(UIDS, [first[u] for u in UIDS], 6)
        tw = RecompileTripwire()
        with tw:
            eng.decode_pipelined(UIDS, [warm[u][-1] for u in UIDS], 12)
        if tw.available:
            assert tw.fresh_compiles == 0

    def test_draft_vocab_mismatch_rejected(self):
        mcfg, params = _gpt2()
        bad = GPT2Config(vocab_size=64, max_seq_len=256, num_layers=1,
                         num_heads=2, hidden_size=16, dtype=jnp.float32)
        eng = InferenceEngineV2(mcfg, params, _cfg(spec_decode="draft"))
        with pytest.raises(ValueError, match="vocab"):
            eng.attach_draft(bad, None)

    @pytest.mark.slow
    def test_draft_small_model_parity(self):
        mcfg, params = _gpt2()
        dcfg, dparams = _gpt2(layers=1, hidden=16, key=5)
        want = _stream(InferenceEngineV2(mcfg, params, _cfg()),
                       REP_PROMPTS, 14)
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(spec_decode="draft", spec_k=4))
        eng.attach_draft(dcfg, dparams)
        got = _stream(eng, REP_PROMPTS, 14)
        assert got == want
        rate = eng.slo_report()["spec_accept_rate"]
        assert rate is not None
        for u in UIDS:
            eng.flush(u)
        assert eng.kv_cache.free_blocks == eng.config.num_blocks

    @pytest.mark.slow
    def test_spec_drain_replay_parity(self):
        # a drain mid-speculation breaks the round loop; the manifest
        # chain (committed tokens only — rejected drafts never entered
        # gen_log) must replay token-identically on a survivor
        mcfg, params = _gpt2()
        cfg_s = _cfg(spec_decode="ngram", spec_k=4)
        want = _stream(InferenceEngineV2(mcfg, params, _cfg()),
                       REP_PROMPTS, 12)
        eng = InferenceEngineV2(mcfg, params, cfg_s)
        first = eng.put(UIDS, [list(p) for p in REP_PROMPTS],
                        _greedy=True)
        part = eng.decode_pipelined(UIDS, [first[u] for u in UIDS], 5)
        m = eng.drain()
        assert m["pool"]["fully_recovered"]
        surv = InferenceEngineV2(mcfg, params, cfg_s)
        rep = surv.replay(m)
        cont = surv.decode_pipelined(UIDS, [rep[u] for u in UIDS], 6)
        got = {u: [first[u]] + part[u] + [rep[u]] + cont[u]
               for u in UIDS}
        assert got == want

    @pytest.mark.slow
    def test_tp2_spec_and_temp0_parity(self):
        # the acceptance-criteria grid: tp∈{1,2} (tp1 is the tier-1
        # suite above), pipeline depth 2, prefix cache on
        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 devices")
        mcfg, params = _gpt2()
        base = dict(depth=2, prefix=True, tp_size=2, max_seqs=2)
        uids = [0, 1]
        prompts = REP_PROMPTS[:2]
        ref = _stream(InferenceEngineV2(mcfg, params, _cfg(**base)),
                      prompts, 10, uids=uids)
        eng_s = InferenceEngineV2(
            mcfg, params, _cfg(**base, spec_decode="ngram", spec_k=4))
        got_s = _stream(eng_s, prompts, 10, uids=uids)
        assert got_s == ref
        sp0 = {u: SamplingParams(temperature=0.0) for u in uids}
        eng_0 = InferenceEngineV2(mcfg, params, _cfg(**base))
        got_0 = _stream(eng_0, prompts, 10, sampling=sp0, uids=uids)
        assert got_0 == ref
        # seeded sampled streams are tp-stable too (the sampler runs on
        # replicated logits after the one pre-sampling gather)
        sp = {u: SamplingParams(temperature=0.8, top_k=8, seed=60 + u)
              for u in uids}
        tp1 = _stream(InferenceEngineV2(
            mcfg, params, _cfg(depth=2, prefix=True, max_seqs=2)),
            prompts, 10, sampling=sp, uids=uids)
        tp2 = _stream(InferenceEngineV2(mcfg, params, _cfg(**base)),
                      prompts, 10, sampling=sp, uids=uids)
        assert tp1 == tp2

    @pytest.mark.slow
    def test_spec_programs_audited_clean(self):
        # sampling/verification add ZERO collectives and zero host
        # callbacks over their greedy siblings
        from deepspeed_tpu.analysis import (CollectiveBudget,
                                            assert_budget,
                                            audit_serve_programs)
        mcfg, params = _gpt2()
        eng = InferenceEngineV2(mcfg, params,
                                _cfg(spec_decode="ngram", spec_k=4))
        reps = audit_serve_programs(
            eng, programs=("step_sample_fb", "decode_verify"))
        for name in ("step_sample_fb", "decode_verify"):
            assert_budget(reps[name],
                          CollectiveBudget(f"tp1-{name}", num_layers=2))
